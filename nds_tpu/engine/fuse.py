"""Pipeline fusion: whole-chain compilation of Filter/Project pipelines.

The eager executor pays a jit dispatch, an HLO round-trip, and (for
projections) a materialized intermediate per plan node. This module is the
engine's whole-stage-codegen seam (the reference gets the equivalent from
Spark fusing scan->filter->project into one compiled loop): a plan-rewrite
pass (`mark_pipelines`) replaces every maximal linear Filter/Project chain
with a single `plan.Pipeline` node, and the executor compiles that chain
as ONE jitted function over the child's device columns.

Fusion mechanics (correctness by construction):

  * The jitted function traces the SAME `expr.Evaluator` the eager path
    runs, so fused and unfused results are identical by construction —
    bit-exact for integer/decimal/date/string/bool data. Float64
    expressions can differ in the FINAL ULP only: XLA's algebraic
    simplifier sees the whole fused expression and may reassociate
    division chains that eager per-op dispatch rounds individually
    (measured <= 1e-12 relative on the windowed-ratio templates, vs the
    validator's 1e-5 epsilon contract). Host-side work the evaluator does
    over column dictionaries (LIKE lookup tables, IN lists, dictionary
    unification) runs once at trace time and bakes into the executable as
    constants — steady-state calls skip it entirely.
  * Outputs that merely pass an input buffer through (filter stages touch
    no column data; plain-Col projection items) are detected at build time
    by tracer identity and PRUNED from the jit signature: the output Table
    references the input buffers directly, and jax drops the then-unused
    inputs, so a fused filter allocates exactly what the eager
    deferred-compaction path allocates (one mask, one queued count) in one
    dispatch instead of one per plan node and expression op.
  * Masks and compaction stay deferred to the pipeline boundary: the fused
    function folds every filter predicate into a single live mask and
    queues the output count asynchronously, exactly like exec._masked.
  * When the input table has no mask (live=None), the live mask is built
    INSIDE the jit from a scalar row count (`count` mode) — no mask buffer
    crosses the boundary at all. When a mask must be passed and the chain
    consumes it (does not pass it through), `engine.fuse_donate=on`
    donates its buffer to the executable. Donation is opt-in: probe-style
    join outputs alias their left input's live mask across operator
    boundaries, and plan-cached tables outlive the statement, so blanket
    donation can invalidate a buffer another table still references (see
    README "Performance").

Shape-bucketed executable reuse: inputs already ride power-of-two capacity
buckets (columnar.bucket_cap), and jax caches one executable per (traced
function, input shapes). `ExecutableCache` keys the traced function by
(pipeline structure fingerprint, input dtype signature) and tracks the
(key, bucket) pairs already compiled, so steady-state re-runs AND
structurally identical queries across a stream reuse executables; the
hit/miss stream is observable as `exec_cache` trace events and enforced by
ci/tier1-check's microbench guard (`profile --min_exec_cache_hit_rate`).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from dataclasses import replace as _dc_replace

import jax
import jax.numpy as jnp

from ..dtypes import FLOAT64, INT64
from ..ops import kernels as K
from . import expr as E
from . import plan as P
from .columnar import Column, Table, bucket_cap, sort_dictionary
from .expr import Evaluator


# ---------------------------------------------------------------------------
# plan rewrite: absorb Filter/Project chains into Pipeline nodes
# ---------------------------------------------------------------------------

# a pipeline child whose live mask may be donated must be a single-consumer
# intermediate no cache retains AND whose mask it owns: scans alias catalog
# buffers; Aggregate/Distinct/SetOp/Window results live in the session plan
# cache across statements; binary Join outputs alias their LEFT input's
# live mask on the left/mark augment paths (exec._augment_join_output), so
# donating their mask would invalidate a buffer the left table still
# references. MultiJoin stays eligible: its inner/cross steps always mint a
# fresh mask (matched / compacted / residual) owned by the output alone.
_NO_DONATE_CHILD = (P.Scan, P.MaterializedScan, P.Join, P.Aggregate,
                    P.Distinct, P.SetOp, P.Window)


def _expr_fusible(e) -> bool:
    """True when an expression can trace inside one jitted function:
    anything except subqueries (they execute whole plans and fetch scalars
    to the host) and aggregate/window functions (never scalar-evaluated).
    Host-side dictionary work (LIKE, IN, string functions) is fine — it
    runs at trace time over concrete dictionaries. Chains that still fail
    to trace (e.g. numeric->string casts, which format device values on
    host) are caught at build time and pinned to the eager path."""
    for x in E.walk(e):
        if isinstance(
            x, (E.SubqueryExpr, E.ScalarSubquery, E.Agg, E.WindowFn)
        ):
            return False
    return True


def _stage_fusible(n) -> bool:
    if isinstance(n, P.Filter):
        return _expr_fusible(n.predicate)
    if isinstance(n, P.Project):
        return bool(n.items) and all(_expr_fusible(e) for e, _ in n.items)
    return False


def _agg_fusible(n: P.Aggregate) -> bool:
    """True when an Aggregate can become a Pipeline's fused tail: plain
    shape only (no grouping sets — the rollup cascade re-aggregates across
    levels; no blocked_union — the windowed path owns those), every
    aggregate decomposable (sum/min/max/count/avg, no distinct — the same
    predicate the blocked-union path gates on), and every key/argument
    expression traceable. Whether the key DOMAIN is small enough for the
    direct scatter is a data property checked at build time (column stats);
    ineligible inputs pin to the eager path per input signature."""
    if n.grouping_sets is not None or n.blocked_union:
        return False
    if not P.aggs_decomposable(n.aggs):
        return False
    for e, _ in n.keys:
        if not _expr_fusible(e):
            return False
    for a, _ in n.aggs:
        if a.arg is not None and not _expr_fusible(a.arg):
            return False
    return True


def _chain_worth_fusing(stages) -> bool:
    """A pure-rename/subset chain gains nothing from compilation (the eager
    path reuses the input column objects outright); fuse only when the
    chain filters or computes something."""
    for s in stages:
        if isinstance(s, P.Filter):
            return True
        if any(not isinstance(e, E.Col) for e, _ in s.items):
            return True
    return False


def _count_refs(node) -> dict:
    """Plan-node reference counts (subquery plans riding in expressions
    included). A shared wrapper must not be absorbed into a pipeline: the
    detached copy would defeat the executor's by-identity result reuse."""
    refs = {}
    seen = set()

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if isinstance(v, P.PlanNode):
                refs[id(v)] = refs.get(id(v), 0) + 1
            if id(v) in seen:
                return
            seen.add(id(v))
            for f in dataclasses.fields(v):
                visit(getattr(v, f.name))
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    visit(node)
    return refs


def _donate_ok_child(cur, refs) -> bool:
    """Plan-level donation clearance for a pipeline's child: the child's
    result must be single-consumer, never retained by a cross-statement
    cache (Aggregate/Distinct/SetOp/Window AND agg-tail Pipelines live in
    the session plan cache), and never an aliasing producer
    (_NO_DONATE_CHILD). WHICH buffers are then actually donatable is a
    runtime property (Column.owned + passthrough analysis in the fused
    call); this gate only proves no OTHER plan node can observe them."""
    if refs.get(id(cur), 1) > 1:
        return False
    if isinstance(cur, _NO_DONATE_CHILD):
        return False
    if isinstance(cur, P.Pipeline) and cur.agg is not None:
        return False  # plan-cached, same as a raw Aggregate
    return True


def mark_pipelines(node: P.PlanNode, fuse_aggs: bool = True):
    """Rewrite every maximal linear Filter/Project chain (anywhere in the
    tree, subquery plans included) into one `plan.Pipeline` node; with
    `fuse_aggs` (conf `engine.fuse_agg`, on by default), a plain
    decomposable Aggregate additionally absorbs the chain FEEDING it and
    becomes the Pipeline's fused aggregate tail — the whole
    scan→filter→project→partial-aggregate run then compiles as one
    dispatch (engine/fuse.py:FusedAggPipeline).

    Returns (root, count): the root itself may head a chain, so callers
    must adopt the returned root; `count` is the number of pipelines
    created (plan-introspection aid for tests/tools)."""
    refs = _count_refs(node)
    made = 0
    seen = set()

    def chain_under(n):
        """(detached stages in execution order, chain input) for the
        maximal fusible single-consumer Filter/Project chain headed at
        `n` (possibly empty)."""
        topdown = []
        cur = n
        while isinstance(cur, (P.Filter, P.Project)) and _stage_fusible(cur):
            # shared nodes keep their identity (the executor caches results
            # by id): a chain stops at the first node with a second parent
            if refs.get(id(cur), 1) > 1:
                break
            topdown.append(cur)
            cur = cur.child
        stages = []
        for s in reversed(topdown):  # execution (innermost-first) order
            if isinstance(s, P.Filter):
                stages.append(P.Filter(predicate=s.predicate, child=None))
            else:
                stages.append(P.Project(items=list(s.items), child=None))
        return stages, cur

    def absorb(n):
        """The Pipeline replacing chain head `n`, or `n` unchanged."""
        nonlocal made
        if (
            fuse_aggs
            and isinstance(n, P.Aggregate)
            and refs.get(id(n), 1) <= 1
            and _agg_fusible(n)
        ):
            # the aggregate tail + the chain feeding it fuse into ONE node;
            # a detached copy keeps the executor's by-identity caches away
            # from the original (which this rewrite discards)
            stages, cur = chain_under(n.child)
            made += 1
            return P.Pipeline(
                stages=stages,
                child=cur,
                donate_ok=_donate_ok_child(cur, refs),
                agg=P.Aggregate(
                    keys=list(n.keys), aggs=list(n.aggs), child=None
                ),
            )
        topdown_stages, cur = chain_under(n)
        if not topdown_stages or not _chain_worth_fusing(topdown_stages):
            return n
        made += 1
        return P.Pipeline(
            stages=topdown_stages,
            child=cur,
            donate_ok=_donate_ok_child(cur, refs),
        )

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if id(v) in seen:
                return
            seen.add(id(v))
            if isinstance(v, P.Sort):
                # single-consumer annotation for the Limit-over-Sort top-k
                # gather (exec._exec_limit): a shared Sort must execute in
                # full once, not top-k for one parent and again in full
                # for the other
                v._topk_safe = refs.get(id(v), 1) <= 1
            if isinstance(v, P.Pipeline):
                # stages/agg are detached (child=None) fragments: never
                # re-absorb them; only the real child subtree recurses —
                # and that child may itself head an absorbable shape (a
                # HAVING chain's pipeline sits over a fusible Aggregate)
                nv = absorb(v.child)
                if nv is not v.child:
                    v.child = nv
                    v.donate_ok = _donate_ok_child(nv, refs)
                visit(v.child)
                return
            for f in dataclasses.fields(v):
                cv = getattr(v, f.name)
                if isinstance(cv, P.PlanNode):
                    nv = absorb(cv)
                    if nv is not cv:
                        # Expr dataclasses are frozen; the plan field of a
                        # ScalarSubquery is excluded from hash/compare, so
                        # in-place rewrite is safe
                        object.__setattr__(v, f.name, nv)
                        cv = nv
                elif isinstance(cv, list):
                    for i, x in enumerate(cv):
                        if isinstance(x, P.PlanNode):
                            nx = absorb(x)
                            if nx is not x:
                                cv[i] = nx
                visit(cv)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    root = absorb(node)
    visit(root)
    return root, made


# ---------------------------------------------------------------------------
# fused evaluation
# ---------------------------------------------------------------------------


class _StatsMarker:
    """Build-time stand-in for an input column's ColStats: an output column
    whose stats object survived the chain untouched maps back to the input
    column index, so every CALL resolves stats from its own input table
    (bounds captured from a trace-time sample would go stale under
    executable reuse across datasets)."""

    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


class _InCol:
    """Input-column metadata a FusedPipeline retains (device buffers must
    not outlive the call — see FusedPipeline.__init__)."""

    __slots__ = ("dtype", "has_valid", "dictionary", "has_stats")

    def __init__(self, dtype, has_valid, dictionary, has_stats):
        self.dtype = dtype
        self.has_valid = has_valid
        self.dictionary = dictionary
        self.has_stats = has_stats


class _FusedBase:
    """Shared input plumbing of the fused callables: flat-argument layout,
    abstract Table reconstruction inside the trace, stage application, and
    ownership-based donation-slot analysis."""

    def _capture_inputs(self, sample: Table):
        self.in_names = list(sample.columns)
        # metadata ONLY — never retain the sample's Column objects: an
        # entry lives for the session and a retained fact-scale .data
        # buffer would pin GBs of device memory past any OOM-recovery wipe
        self.in_meta = [
            _InCol(
                c.dtype,
                c.valid is not None,
                c.dictionary,
                c.stats is not None,
            )
            for c in sample.columns.values()
        ]
        # the dictionaries ARE retained deliberately: the cache key uses
        # id(dictionary), which stays truthful only while the object is
        # alive (a recycled address must not alias a new dict), and the
        # trace bakes their lookup tables in. Host-side, dimension-sized.

    def _input_specs(self, sample: Table):
        specs = []
        if self.live_mode == "count":
            specs.append(jax.ShapeDtypeStruct((), jnp.int32))
        elif self.live_mode in ("mask", "mask_pass"):
            specs.append(jax.ShapeDtypeStruct((sample.cap,), jnp.bool_))
        for c in sample.columns.values():
            specs.append(jax.ShapeDtypeStruct(c.data.shape, c.data.dtype))
        for c in sample.columns.values():
            if c.valid is not None:
                specs.append(jax.ShapeDtypeStruct((sample.cap,), jnp.bool_))
        return specs

    def _flat_inputs(self, flat):
        i = 0
        live = None
        if self.live_mode == "count":
            n = flat[0]
            i = 1
        elif self.live_mode in ("mask", "mask_pass"):
            live = flat[0]
            i = 1
        datas = flat[i:i + len(self.in_meta)]
        i += len(self.in_meta)
        cap = int(datas[0].shape[0]) if datas else (
            int(live.shape[0]) if live is not None else 0
        )
        if self.live_mode == "count":
            live = jnp.arange(cap, dtype=jnp.int32) < n
        cols = {}
        vi = i
        for ci, (name, c, d) in enumerate(
            zip(self.in_names, self.in_meta, datas)
        ):
            valid = None
            if c.has_valid:
                valid = flat[vi]
                vi += 1
            cols[name] = Column(
                d, c.dtype, valid, c.dictionary,
                _StatsMarker(ci) if c.has_stats else None,
            )
        nrows = jnp.sum(live, dtype=jnp.int32) if live is not None else 0
        return Table(cols, nrows, live=live)

    def _apply_stages(self, t: Table) -> Table:
        """The evaluator chain, stage by stage, inside the trace — the SAME
        Evaluator the eager path runs, so fused results match eager by
        construction."""
        for s in self.stages:
            ev = Evaluator(t)
            if isinstance(s, P.Filter):
                pr = ev.eval(s.predicate)
                mask = pr.data.astype(bool)
                if pr.valid is not None:
                    mask = mask & pr.valid
                mask = mask & t.row_mask()
                t = Table(
                    dict(t.columns), jnp.sum(mask, dtype=jnp.int32),
                    live=mask,
                )
            else:
                cols = {name: ev.eval(e) for e, name in s.items}
                t = Table(cols, t.nrows_lazy, live=t.live)
        return t

    def _flat_args(self, table: Table):
        flat = []
        if self.live_mode == "count":
            # asarray, not int(): the count may be a still-queued 0-d
            # device scalar and must not force a sync here
            flat.append(jnp.asarray(table.nrows_lazy, dtype=jnp.int32))
        elif self.live_mode in ("mask", "mask_pass"):
            flat.append(table.row_mask())
        for c in table.columns.values():
            flat.append(c.data)
        for c in table.columns.values():
            if c.valid is not None:
                flat.append(c.valid)
        return flat

    def _analyze_donation(self, fn, specs, cap):
        """Build-time donation feasibility: (consumed slots, output aval
        templates). `consumed` is the flat input slots the compiled body
        actually reads (jaxpr dead-code elimination — an owned input that
        only fed a pruned passthrough output, or a stage value a later
        projection dropped, is DCE'd by XLA). The templates are the
        computed outputs' (dtype, shape) with the sample capacity
        normalized to "cap": jax only aliases a donated buffer into an
        output with the IDENTICAL aval, so donating without a matching
        output reclaims nothing, emits jax's unusable-donation warning on
        every compile, and forks a pointless executable variant per
        owned-pattern. (None, None) means "donate whatever ownership
        allows" — the analysis rides a jax-internal API, and any drift
        only costs those warnings, never correctness."""
        try:
            # build-time-only cold path (once per compiled executable, never
            # per call) AND a jax-internal module kept inside the guarding
            # try so an import-time rename degrades like any other drift
            # nds-lint: disable=local-import
            from jax.interpreters import partial_eval as pe

            jaxpr = jax.make_jaxpr(fn)(*specs).jaxpr
            _, used = pe.dce_jaxpr(jaxpr, [True] * len(jaxpr.outvars))
            consumed = frozenset(i for i, u in enumerate(used) if u)
            outs = [
                (
                    v.aval.dtype,
                    tuple(
                        "cap" if d == cap else d for d in v.aval.shape
                    ),
                )
                for v in jaxpr.outvars
            ]
            return consumed, outs
        except Exception:
            return None, None

    # -- AOT-cached execution ---------------------------------------------
    def _init_aot(self, aot, fp, conf_sig, sample, kind: str,
                  with_stats: bool):
        """Arm persistent-executable resolution (engine/aotcache.py): the
        base key half that is fixed at build time — pipeline kind, stage
        fingerprint, content-stable input signature, relevant engine conf.
        The per-bucket half (avals + donation slots) joins at dispatch.
        `aot=None` keeps the classic in-process jit path untouched."""
        self._aot = aot
        self._aot_exec = {}  # (avals, slots) -> (compiled, from_disk)
        if aot is None:
            self._aot_base = None
            return
        self._aot_base = (
            kind, fp, aot.content_signature(sample, with_stats=with_stats),
            tuple(conf_sig or ()),
        )

    def _dispatch(self, flat, slots: tuple):
        """Run the traced body over `flat` with `slots` donated.

        Without an AOT cache this is the classic path: one jax.jit per
        donation variant, executables keyed per shape bucket inside jax.
        With one, every (avals, slots) bucket resolves its OWN compiled
        executable — disk hit deserializes (a fresh process skips XLA
        entirely), miss pays jit(fn).lower(avals).compile() ONCE and
        serializes the result for every future process. A deserialized
        executable that fails at call time is quarantined and replaced by
        a fresh compile (never a crash, and donation-armed calls re-raise
        instead of retrying over possibly-invalidated buffers)."""
        if self._aot is None:
            if slots:
                jitted = self._jit_donate.get(slots)
                if jitted is None:
                    jitted = self._jit_donate[slots] = jax.jit(
                        self._fn, donate_argnums=slots
                    )
                return jitted(*flat)
            return self._jit(*flat)
        avals = tuple((tuple(a.shape), str(a.dtype)) for a in flat)
        rec = self._aot_exec.get((avals, slots))
        if rec is None:
            rec = self._aot_exec[(avals, slots)] = self._aot_resolve(
                flat, slots, avals
            )
        compiled, from_disk = rec
        try:
            return compiled(*flat)
        except Exception:
            if not from_disk:
                raise
            # keyed correctly but unusable on this runtime (e.g. a stale
            # serialization format): quarantine the entry so NO process
            # (this one included) keeps loading it, and forget the dead
            # in-memory rec so the next attempt recompiles fresh
            self._aot.quarantine_key(self._aot_key(avals, slots))
            self._aot_exec.pop((avals, slots), None)
            if slots:
                # the failed call may already have donated (invalidated)
                # input buffers: a retry over them would read garbage —
                # surface the failure (the ladder re-runs the query, which
                # now compiles cleanly)
                raise
            compiled = self._aot_compile(flat, slots)
            self._aot_exec[(avals, slots)] = (compiled, False)
            return compiled(*flat)

    def _aot_key(self, avals, slots) -> dict:
        kind, fp, sig, conf_sig = self._aot_base
        return self._aot.entry_key(kind, fp, sig, avals, slots, conf_sig)

    def _aot_compile(self, flat, slots):
        specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]
        return jax.jit(
            self._fn, donate_argnums=slots or ()
        ).lower(*specs).compile()

    def _aot_resolve(self, flat, slots, avals):
        """(compiled, from_disk) for one (avals, slots) bucket: disk load
        first, else compile + persist."""
        key = self._aot_key(avals, slots)
        compiled = self._aot.load(key)
        if compiled is not None:
            return compiled, True
        compiled = self._aot_compile(flat, slots)
        self._aot.store(key, compiled)
        return compiled, False

    def _donate_slots(self, table: Table, flat) -> tuple:
        """Flat arg indices safe AND useful to donate for THIS call: the
        consumed live-mask input (the plan rewrite's donate_ok gate already
        proved the child single-consumer and its mask freshly minted), plus
        every data/validity buffer the producer marked Column.owned —
        excluding buffers that pass through to the output, buffers the
        executable never consumes, buffers with no same-aval computed
        output left to alias into (see _analyze_donation for both), and
        buffers appearing more than once in the argument list (a `select
        k, k k2` projection feeds one buffer twice; donating either copy
        would invalidate the other)."""
        pt = getattr(self, "passthrough", None) or ()
        pt_srcs = {s for s in pt if s is not None}
        consumed = getattr(self, "_consumed", None)
        templates = getattr(self, "_out_avals", None)
        avail = None
        if templates is not None:
            cap = table.cap
            avail = {}
            for dt, shape in templates:
                key = (
                    dt, tuple(cap if d == "cap" else d for d in shape)
                )
                avail[key] = avail.get(key, 0) + 1

        def ok(slot):
            if (
                slot in pt_srcs
                or (consumed is not None and slot not in consumed)
                or counts[id(flat[slot])] != 1
            ):
                return False
            if avail is None:
                return True
            key = (flat[slot].dtype, tuple(flat[slot].shape))
            if avail.get(key, 0) <= 0:
                return False
            avail[key] -= 1  # one output buffer aliases one donation
            return True

        counts = {}
        for x in flat:
            counts[id(x)] = counts.get(id(x), 0) + 1
        slots = []
        i = 0
        if self.live_mode == "count":
            i = 1  # 0-d scalar: nothing to donate
        elif self.live_mode in ("mask", "mask_pass"):
            if self.live_mode == "mask" and ok(0):
                slots.append(0)
            i = 1
        cols = list(table.columns.values())
        for ci, c in enumerate(cols):
            slot = i + ci
            if c.owned and ok(slot):
                slots.append(slot)
        vi = i + len(cols)
        for c in cols:
            if c.valid is None:
                continue
            if c.owned and ok(vi):
                slots.append(vi)
            vi += 1
        return tuple(slots)


class FusedPipeline(_FusedBase):
    """One compiled Filter/Project chain for one input signature.

    Built once per (stage fingerprint, input signature); jax adds one
    executable per input capacity bucket underneath the single traced
    callable. Construction traces the chain abstractly (jax.eval_shape) to
    capture output structure and the passthrough map; a chain that cannot
    trace raises, and the ExecutableCache pins its signature to the eager
    path."""

    def __init__(self, stages, sample: Table, aot=None, fp=None,
                 conf_sig=()):
        """aot/fp/conf_sig: persistent-executable resolution
        (engine/aotcache.py) — `aot` is the session AotCache (or None for
        the classic jit path), `fp` the pipeline's stage fingerprint, and
        `conf_sig` the compiled-code-relevant engine conf values that
        join the on-disk entry key."""
        self.stages = stages
        self._capture_inputs(sample)
        self.has_filter = any(isinstance(s, P.Filter) for s in stages)
        # live handling: "count" (live=None input: the mask is built inside
        # the jit from a scalar row count — no mask buffer at the boundary),
        # "mask" (explicit mask input), "none" (pure projection over an
        # unmasked table: liveness never enters the jit)
        if self.has_filter:
            self.live_mode = "count" if sample.live is None else "mask"
        else:
            self.live_mode = "none" if sample.live is None else "mask_pass"
        self.out_meta = None
        self.passthrough = None
        jax.eval_shape(self._run_full, *self._input_specs(sample))
        # outputs that pass an input buffer through are reassembled from
        # the caller's own columns; pruning them from the jit lets jax drop
        # the then-unused inputs entirely (no copies through the
        # executable)
        self._kept = [
            i for i, src in enumerate(self.passthrough) if src is None
        ]
        self._consumed, self._out_avals = self._analyze_donation(
            self._run_kept, self._input_specs(sample), sample.cap
        )
        self._fn = self._run_kept
        self._jit = jax.jit(self._run_kept)
        self._jit_donate = {}  # donate-slot tuple -> jitted callable
        self._init_aot(aot, fp, conf_sig, sample, "pipeline",
                       with_stats=False)

    # -- traced body ------------------------------------------------------
    def _run_full(self, *flat):
        t = self._apply_stages(self._flat_inputs(flat))
        # flatten outputs + capture structure (side effect: runs at trace
        # time only, with identical values on every trace)
        flat_out = []
        if self.has_filter:
            flat_out.append(t.nrows_lazy)  # queued count (0-d device)
            flat_out.append(t.live)
        self.out_data_base = len(flat_out)
        for c in t.columns.values():
            flat_out.append(c.data)
        valid_slots = []
        for c in t.columns.values():
            if c.valid is not None:
                valid_slots.append(len(flat_out))
                flat_out.append(c.valid)
            else:
                valid_slots.append(None)
        self.out_valid_slots = valid_slots
        self.out_meta = [
            (name, c.dtype, c.dictionary, c.stats)
            for name, c in t.columns.items()
        ]
        self.passthrough = [
            next((j for j, a in enumerate(flat) if o is a), None)
            for o in flat_out
        ]
        return tuple(flat_out)

    def _run_kept(self, *flat):
        out = self._run_full(*flat)
        return tuple(out[i] for i in self._kept)

    # -- call -------------------------------------------------------------
    def call(self, table: Table, donate: bool) -> Table:
        flat = self._flat_args(table)
        slots = self._donate_slots(table, flat) if donate else ()
        out = self._dispatch(flat, slots)
        # reassemble: computed slots from the executable, passthrough
        # slots straight from the caller's own buffers
        full = [None] * len(self.passthrough)
        for slot, v in zip(self._kept, out):
            full[slot] = v
        for slot, src in enumerate(self.passthrough):
            if src is not None:
                full[slot] = flat[src]
        if self.has_filter:
            nrows, live = full[0], full[1]
        else:
            nrows, live = table.nrows_lazy, table.live
        in_cols = list(table.columns.values())
        cols = {}
        for k, (name, dtype, dic, st) in enumerate(self.out_meta):
            data = full[self.out_data_base + k]
            vslot = self.out_valid_slots[k]
            valid = None if vslot is None else full[vslot]
            stats = (
                in_cols[st.idx].subset_stats()
                if isinstance(st, _StatsMarker)
                else None  # never trust stats minted at trace time
            )
            cols[name] = Column(data, dtype, valid, dic, stats)
        return Table(
            cols, nrows, live=live, unique_key=self._out_unique_key(table)
        )

    def _out_unique_key(self, table: Table):
        """Replay name flow host-side: filters preserve the input's unique
        key; projections keep it only when every key column survives as a
        plain rename (mirrors exec._project_table)."""
        uk = table.unique_key
        names = set(table.columns)
        for s in self.stages:
            if uk is None:
                return None
            if isinstance(s, P.Filter):
                continue
            renames = {}
            for e, name in s.items:
                if isinstance(e, E.Col):
                    key = f"{e.table}.{e.name}" if e.table else e.name
                    if key not in names and e.name in names:
                        key = e.name
                    renames.setdefault(key, name)
            uk = (
                frozenset(renames[k] for k in uk)
                if all(k in renames for k in uk)
                else None
            )
            names = {n for _, n in s.items}
        return uk


_DIRECT_AGG_MAX_DOMAIN = 1 << 22  # mirrors exec._DIRECT_AGG_MAX_DOMAIN


class _AggKey:
    """Trace-captured metadata of one group-key column (build-time probe):
    enough to resolve static bounds and reconstruct the key column from
    occupied cell codes at call time."""

    __slots__ = ("dtype", "dictionary", "has_valid", "stats_idx")

    def __init__(self, col: Column):
        self.dtype = col.dtype
        self.dictionary = col.dictionary
        self.has_valid = col.valid is not None
        self.stats_idx = (
            col.stats.idx if isinstance(col.stats, _StatsMarker) else None
        )


class FusedAggPipeline(_FusedBase):
    """A Filter/Project chain PLUS its decomposable aggregate tail,
    compiled as one dispatch.

    The traced body runs the evaluator chain, folds filters into the live
    mask, computes mixed-radix group codes elementwise (the executor's
    direct sort-free aggregation scheme, exec._try_direct_agg — bounds are
    baked as trace constants, so the input signature carries them), and
    scatters every aggregate into a domain-bucket cell array via the same
    segment_reduce kernels the eager path dispatches one by one. The call
    then pays ONE host sync for the occupied-group count (exactly what the
    eager direct path pays), compacts the occupied cells, reconstructs the
    key columns from the cell codes, and gathers the aggregate values —
    small gcap-sized work after the single fact-scale dispatch.

    Build raises (and the ExecutableCache pins the signature to the eager
    path) when any key lacks static bounds, the combined domain exceeds
    the direct-aggregation cap, or an argument cannot trace — the exact
    inputs the eager path would route to its sort-based aggregation."""

    def __init__(self, stages, agg: P.Aggregate, sample: Table, aot=None,
                 fp=None, conf_sig=()):
        self.stages = stages
        self.agg = agg
        self._capture_inputs(sample)
        # per-input-column host stats (vmin, vmax): the probe maps plain
        # key columns back to these; part of the cache signature, so a
        # dataset with different bounds builds its own entry
        self.in_stats = [
            (int(c.stats.vmin), int(c.stats.vmax))
            if c.stats is not None
            else None
            for c in sample.columns.values()
        ]
        # aggregation needs liveness even for a pure projection chain
        self.live_mode = "count" if sample.live is None else "mask"
        specs = self._input_specs(sample)
        # phase 1: probe the chain + key expressions abstractly to learn
        # each key's dtype/dictionary/validity and which input column its
        # stats flow from (tracer identity via _StatsMarker)
        self.key_meta = None
        jax.eval_shape(self._probe_keys, *specs)
        self._resolve_bounds()
        # phase 2: trace the real body (bounds now baked) to capture the
        # aggregate output slot layout
        self.agg_meta = None
        jax.eval_shape(self._run_agg, *specs)
        self.passthrough = ()  # aggregate outputs never alias inputs
        # agg outputs live at the (build-constant) domain cap, never the
        # input cap: normalize against sample.cap anyway so a coincident
        # equality generalizes the same way the pipeline case does
        self._consumed, self._out_avals = self._analyze_donation(
            self._run_agg, specs, sample.cap
        )
        self._fn = self._run_agg
        self._jit = jax.jit(self._run_agg)
        self._jit_donate = {}
        # stats fold into the content signature: the mixed-radix bounds
        # bake into the trace, so a dataset with different bounds is a
        # different executable on disk too
        self._init_aot(aot, fp, conf_sig, sample, "agg_pipeline",
                       with_stats=True)

    # -- build ------------------------------------------------------------
    def _probe_keys(self, *flat):
        t = self._apply_stages(self._flat_inputs(flat))
        ev = Evaluator(t)
        self.key_meta = [
            _AggKey(ev.eval(e)) for e, _ in self.agg.keys
        ]
        return ()

    def _resolve_bounds(self):
        mins, ranges = [], []
        domain = 1
        for km in self.key_meta:
            # the same bound sources the eager direct path accepts:
            # dictionary codes / bools span statically, int-like keys need
            # ColStats that survived the chain
            if km.dtype.is_string:
                if km.dictionary is None or len(km.dictionary) == 0:
                    raise ValueError("string key without a dictionary")
                kmin, kmax = 0, len(km.dictionary) - 1
            elif km.dtype.kind == "bool":
                kmin, kmax = 0, 1
            elif km.dtype.kind in ("int32", "int64", "date"):
                st = (
                    self.in_stats[km.stats_idx]
                    if km.stats_idx is not None
                    else None
                )
                if st is None:
                    raise ValueError("key without static bounds")
                kmin, kmax = st
            else:
                raise ValueError(f"key dtype {km.dtype} not direct-aggable")
            krange = kmax - kmin + 1 + (1 if km.has_valid else 0)
            domain *= krange
            if domain > _DIRECT_AGG_MAX_DOMAIN:
                raise ValueError("group-key domain exceeds the direct cap")
            mins.append(kmin)
            ranges.append(krange)
        self.mins = mins
        self.ranges = ranges
        self.domain_cap = bucket_cap(domain)

    # -- traced body ------------------------------------------------------
    def _run_agg(self, *flat):
        t = self._apply_stages(self._flat_inputs(flat))
        live = t.row_mask()
        ev = Evaluator(t)
        dc = self.domain_cap
        # mixed-radix group code per row (mirrors K.direct_gid; NULL takes
        # the reserved 0 code per nullable key, dead rows park at cell 0
        # and are excluded by the live/weight masks)
        gid = jnp.zeros(live.shape[0], jnp.int64)
        for (e, _), kmin, krange in zip(self.agg.keys, self.mins,
                                        self.ranges):
            c = ev.eval(e)
            d = c.data
            if d.dtype == jnp.bool_:
                d = d.astype(jnp.int32)
            code = d.astype(jnp.int64) - kmin
            if c.valid is not None:
                code = jnp.where(c.valid, code + 1, 0)
            gid = gid * krange + code
        gid = jnp.where(live, gid, 0).astype(jnp.int32)
        occ = jnp.zeros(dc, bool).at[gid].max(live, mode="drop")
        flat_out = [occ]
        agg_meta = []
        for a, name in self.agg.aggs:
            fn = a.fn
            if fn == "count" and a.arg is None:
                counts = K.segment_reduce(
                    live.astype(jnp.int64), gid, live, dc, "count"
                )
                agg_meta.append(("count", name, INT64, None,
                                 len(flat_out), None))
                flat_out.append(counts)
                continue
            c = ev.eval(a.arg)
            weight = live
            if c.valid is not None:
                weight = weight & c.valid
            sdata = c.data
            dictionary = None
            if c.dtype.is_string:
                if fn not in ("min", "max"):
                    raise ValueError(f"agg {fn} on string column")
                # rank transform bakes at trace time; comparing rank codes
                # is comparing strings (mirrors exec._eval_agg)
                sdata, dictionary = sort_dictionary(c)
            if fn == "count":
                counts = K.segment_reduce(sdata, gid, weight, dc, "count")
                agg_meta.append(("count", name, INT64, None,
                                 len(flat_out), None))
                flat_out.append(counts)
            elif fn in ("sum", "min", "max"):
                red, counts = K.segment_reduce_with_count(
                    sdata, gid, weight, dc, fn
                )
                dtype = c.dtype
                if c.dtype.is_string:
                    red = red.astype(jnp.int32)
                elif fn == "sum" and dtype.kind == "int32":
                    dtype = INT64
                    red = red.astype(jnp.int64)
                agg_meta.append(("valcnt", name, dtype, dictionary,
                                 len(flat_out), len(flat_out) + 1))
                flat_out.append(red)
                flat_out.append(counts)
            elif fn == "avg":
                # the jit returns RAW (sum, count); the division runs
                # eagerly in _agg_column with the eager path's exact op
                # sequence — inside the jit XLA reassociates the two
                # divisions and the result drifts an ulp from eager
                s, n = K.segment_reduce_with_count(sdata, gid, weight, dc,
                                                   "sum")
                scale = c.dtype.scale if c.dtype.is_decimal else None
                agg_meta.append(("avg", name, FLOAT64, scale,
                                 len(flat_out), len(flat_out) + 1))
                flat_out.append(s)
                flat_out.append(n)
            else:
                raise ValueError(f"aggregate {fn} not fusible")
        self.agg_meta = agg_meta
        return tuple(flat_out)

    # -- call -------------------------------------------------------------
    def call(self, table: Table, donate: bool) -> Table:
        flat = self._flat_args(table)
        slots = self._donate_slots(table, flat) if donate else ()
        out = self._dispatch(flat, slots)
        in_cols = list(table.columns.values())
        if not self.agg.keys:
            # global aggregate: exactly one output row (cell 0), over empty
            # input included — domain_cap equals the eager path's
            # bucket_cap(1) group capacity, so arrays line up unchanged
            cols = {}
            for meta in self.agg_meta:
                cols.update(self._agg_column(meta, out, None))
            return Table(cols, 1, unique_key=frozenset())
        occ = out[0]
        # the ONE host sync of the fused path — the same occupied-group
        # count the eager direct aggregation fetches (K.mask_count)
        ngroups = int(jnp.sum(occ, dtype=jnp.int32))
        if ngroups == 0:
            return self._empty_output()
        gcap = bucket_cap(ngroups)
        occ_cells = K.compact_indices(occ, gcap).astype(jnp.int64)
        # reconstruct key columns from the occupied cell codes (reverse
        # mixed-radix decomposition; last key is least significant)
        codes = []
        rem = occ_cells
        for krange in reversed(self.ranges):
            codes.append(rem % krange)
            rem = rem // krange
        codes.reverse()
        cols = {}
        n_keys = len(self.agg.keys)
        for (e, name), km, code, kmin in zip(
            self.agg.keys, self.key_meta, codes, self.mins
        ):
            if km.has_valid:
                valid = code != 0
                value = jnp.where(valid, kmin + code - 1, 0)
            else:
                valid = None
                value = kmin + code
            stats = None
            if km.stats_idx is not None:
                base = in_cols[km.stats_idx].subset_stats()
                if base is not None:
                    stats = _dc_replace(base, unique=(n_keys == 1))
            cols[name] = Column(
                value.astype(km.dtype.device_np_dtype()), km.dtype,
                valid, km.dictionary, stats, owned=True,
            )
        for meta in self.agg_meta:
            cols.update(self._agg_column(meta, out, occ_cells))
        return Table(
            cols, ngroups,
            unique_key=frozenset(n for _, n in self.agg.keys),
        )

    def _agg_column(self, meta, out, cells):
        # 4th slot: dictionary for valcnt kinds, decimal scale for avg
        kind, name, dtype, dictionary, s1, s2 = meta

        def gather(slot):
            arr = out[slot]
            if cells is None:
                return arr[: bucket_cap(1)]
            return arr[cells]

        if kind == "count":
            return {name: Column(gather(s1).astype(jnp.int64), INT64,
                                 owned=True)}
        if kind == "avg":
            s, n = gather(s1), gather(s2)
            nz = jnp.maximum(n, 1)
            # eager _eval_agg's exact division sequence (elementwise, so
            # running it post-gather is value-identical to pre-gather)
            if dictionary is not None:
                val = s.astype(jnp.float64) / (10**dictionary) / nz
            else:
                val = s.astype(jnp.float64) / nz
            return {name: Column(val, FLOAT64, n > 0, owned=True)}
        red = gather(s1)
        cnt = gather(s2)
        return {
            name: Column(red, dtype, cnt > 0, dictionary, owned=True)
        }

    def _empty_output(self) -> Table:
        """Mirror of the eager empty-grouped-aggregate stub
        (exec._agg_output with ngroups=0): 1-capacity columns, zero rows,
        every aggregate stubbed as a null INT64."""
        cols = {}
        for (e, name), km in zip(self.agg.keys, self.key_meta):
            cols[name] = Column(
                jnp.zeros(1, km.dtype.device_np_dtype()), km.dtype,
                jnp.zeros(1, bool), km.dictionary,
            )
        for _, name, _, _, _, _ in self.agg_meta:
            cols[name] = Column(
                jnp.zeros(1, jnp.int64), INT64, jnp.zeros(1, bool)
            )
        return Table(cols, 0)


def input_signature(table: Table, with_stats: bool = False):
    """Hashable identity of an input table's device layout: liveness mode,
    column names, dtypes, validity presence, dictionary identity (codes are
    only meaningful relative to their dictionary, and trace-time lookup
    tables bake it in). Capacity is deliberately absent — jax keys
    executables per shape bucket underneath one traced callable, which is
    exactly the shape-bucketed reuse: a query re-run (same bucket) or a
    structurally identical query at another bucket share the trace.

    `with_stats` (aggregate-tail pipelines) folds each column's host-side
    (vmin, vmax) bounds in: the fused aggregate bakes key bounds into the
    trace as mixed-radix constants, so a dataset with different bounds
    must build (and cache) its own entry."""
    sig = [table.live is not None]
    for name, c in table.columns.items():
        entry = (
            name,
            repr(c.dtype),
            c.valid is not None,
            id(c.dictionary) if c.dictionary is not None else None,
        )
        if with_stats:
            entry = entry + (
                (int(c.stats.vmin), int(c.stats.vmax))
                if c.stats is not None
                else None,
            )
        sig.append(entry)
    return tuple(sig)


class ExecutableCache:
    """Session-level cache of FusedPipeline builds keyed by (pipeline
    structure fingerprint, input signature), with per-(key, bucket)
    hit/miss accounting — the bucket level is where XLA actually compiles.
    Entries pin their dictionaries (see input_signature); a failed build is
    pinned as None so the executor stops re-attempting the fuse. LRU by
    entry count: entries hold host-side trace machinery, not device
    buffers."""

    def __init__(self, max_entries: int = 512):
        self.max_entries = max_entries
        self.map = OrderedDict()  # (fp, sig) -> FusedPipeline | None
        self.buckets = set()  # (fp, sig, cap) already compiled
        self.hits = 0
        self.misses = 0

    def lookup(self, fp, sig, cap, build):
        """(FusedPipeline | None, hit: bool)."""
        key = (fp, sig)
        if key in self.map:
            entry = self.map[key]
            self.map.move_to_end(key)
        else:
            try:
                entry = build()
            except Exception:
                entry = None  # unfusible chain: pin to the eager path
            self.map[key] = entry
            while len(self.map) > self.max_entries:
                old_key, _ = self.map.popitem(last=False)
                self.buckets = {
                    b for b in self.buckets if b[:2] != old_key
                }
        if entry is None:
            return None, False
        bkey = (fp, sig, cap)
        hit = bkey in self.buckets
        if hit:
            self.hits += 1
        else:
            self.misses += 1
            self.buckets.add(bkey)
        return entry, hit

    def clear(self):
        self.map.clear()
        self.buckets.clear()
