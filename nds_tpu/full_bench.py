"""Whole-benchmark orchestrator: the reference `nds_bench.py` equivalent.

Phase plan (reference: nds/nds_bench.py:34-42):
  0. data generation (+ per-stream --update refresh sets)   [not timed]
  1. Load Test (transcode)                    -> Tload, RNGSEED timestamp
  2. query stream generation (RNGSEED = load end timestamp, Spec 4.3.1)
  3. Power Test                               -> Tpower
  4. Throughput Test 1 (streams 1..S)         -> Ttt1
  5. Maintenance Test 1 (refresh sets 1..S)   -> Tdm1
  6. Throughput Test 2 (streams S+1..2S)      -> Ttt2
  7. Maintenance Test 2 (refresh sets S+1..)  -> Tdm2
  metric = int(SF * Sq*99 / (Tpt*Ttt*Tdm*Tld)^(1/4))   -> metrics.csv

Each phase shells out to its CLI (process boundary, like the reference's
subprocess.run of spark-submit) and state passes through report files on
disk, so any phase can be skipped and resumed from prior reports
(reference: nds_bench.py:367-497; skip semantics nds/README.md:499-503).

Failure domain: the orchestrator checkpoints an atomically-written
`bench_state.json` after every completed phase, `--resume` derives the
skip set from it (no more manual `skip:` editing after a multi-hour run
dies), classified-transient phase failures retry with a bounded budget
(NDS_PHASE_RETRIES), and every phase runner is a fault-injection site
(e.g. `crash:power_test` in NDS_FAULT_SPEC) so the resume path is
deterministically testable.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import subprocess
import sys
import time

import yaml

from . import faults
from .io.fs import fs_open, fs_open_atomic, get_fs, is_remote
from .obs import reader as obs_reader
from .obs import trace as obs_trace
from .throughput import round_up_to_nearest_10_percent


def get_yaml_params(yaml_file):
    with open(yaml_file) as f:
        return yaml.safe_load(f)


# ---------------------------------------------------------------------------
# report-file parsers (state passes between phases on disk)
# ---------------------------------------------------------------------------


def get_load_end_timestamp(load_report_file):
    """RNGSEED for stream generation = load end timestamp (Spec 4.3.1);
    re-read from the load report (reference: nds_bench.py:60-74)."""
    with open(load_report_file) as f:
        for line in f:
            if "RNGSEED used" in line:
                return int(line.split(":")[1].strip())
    raise ValueError(
        f"RNGSEED not found in load report {load_report_file}; "
        "re-run the Load Test or fix the report path"
    )


def get_load_time(load_report_file):
    with open(load_report_file) as f:
        for line in f:
            if "Load Test Time" in line:
                return float(line.split(":")[1].strip().split(" ")[0])
    raise ValueError(f"Load Test Time not found in {load_report_file}")


def get_power_time(power_report_file):
    """Power Test elapsed seconds from the CSV time log (ms -> s, rounded
    up to 0.1 s; reference: nds_bench.py:92-104,207-208)."""
    import csv

    with open(power_report_file) as f:
        for row in csv.reader(f):
            if len(row) >= 3 and row[1] == "Power Test Time":
                return round_up_to_nearest_10_percent(float(row[2]) / 1000)
    raise ValueError(f"Power Test Time not found in {power_report_file}")


def get_refresh_time(maintenance_report_file):
    import csv

    with open(maintenance_report_file) as f:
        for row in csv.reader(f):
            if len(row) >= 2 and row[1] == "Data Maintenance Time":
                return float(row[2])
    raise ValueError(
        f"Data Maintenance Time not found in {maintenance_report_file}"
    )


def get_throughput_time(time_log_base, num_streams, first_or_second):
    from .throughput import _ttt_from_logs

    return _ttt_from_logs(
        get_stream_range(num_streams, first_or_second), time_log_base
    )


def get_maintenance_time(report_base, num_streams, first_or_second):
    tdm = 0.0
    for i in get_stream_range(num_streams, first_or_second):
        tdm += get_refresh_time(f"{report_base}_{i}.csv")
    return round_up_to_nearest_10_percent(tdm)


def get_stream_range(num_streams, first_or_second):
    """Streams of one Throughput Test. num_streams=9: test 1 -> [1..4],
    test 2 -> [5..8] (stream 0 is the Power stream;
    reference: nds_bench.py:126-135)."""
    if first_or_second == 1:
        return list(range(1, num_streams // 2 + 1))
    return list(range(num_streams // 2 + 1, num_streams))


def get_throughput_stream_nums(num_streams, first_or_second):
    return ",".join(str(x) for x in get_stream_range(num_streams, first_or_second))


# ---------------------------------------------------------------------------
# composite metric (reference: nds_bench.py:334-357)
# ---------------------------------------------------------------------------


def get_perf_metric(scale_factor, sq, tload, tpower, ttt1, ttt2, tdm1, tdm2):
    """int(SF * Q / (Tpt*Ttt*Tdm*Tld)^(1/4)), quantities in decimal hours;
    Q = Sq*99, Tld weighted 0.01*Sq (TPC-DS Spec 7.6.3)."""
    q = sq * 99
    tpt = (tpower * sq) / 3600
    ttt = (ttt1 + ttt2) / 3600
    tdm = (tdm1 + tdm2) / 3600
    tld = (0.01 * sq * tload) / 3600
    # reference truncates SF to int (nds_bench.py:356); float() keeps
    # fractional smoke scales (SF<1) from collapsing the metric to 0 and is
    # identical for the integral SFs the spec defines
    return int(float(scale_factor) * q / (tpt * ttt * tdm * tld) ** (1 / 4))


def write_metrics_report(report_path, metrics_map):
    with fs_open_atomic(report_path, "w") as f:
        for key, value in metrics_map.items():
            f.write(f"{key},{value}\n")


# ---------------------------------------------------------------------------
# checkpoint state (crash-safe resume without manual `skip:` editing)
# ---------------------------------------------------------------------------

#: orchestrator phase order; bench_state.json records completion per name.
#: maintenance_under_load is OPT-IN (params `maintenance_under_load:
#: enabled: true`) and sits after the timed TPC phases so its racing
#: commits/vacuum can never perturb the composite metric's inputs.
PHASES = (
    "data_gen",
    "load_test",
    "gen_streams",
    "power_test",
    "throughput_test_1",
    "maintenance_test_1",
    "throughput_test_2",
    "maintenance_test_2",
    "maintenance_under_load",
)


def params_fingerprint(params) -> str:
    """Stable digest of the bench config: a resume against a different
    config would silently mix phase outputs from two benchmarks."""
    blob = json.dumps(params, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def bench_state_path(params) -> str:
    explicit = params.get("bench_state_path")
    if explicit:
        return str(explicit)
    base = os.path.dirname(str(params.get("metrics_report_path", "")))
    return os.path.join(base, "bench_state.json") if base else "bench_state.json"


class BenchState:
    """Phase-completion checkpoint, atomically rewritten after every phase
    so the on-disk file is always a complete, parseable snapshot."""

    def __init__(self, path: str, fingerprint: str, phases=None):
        self.path = path
        self.fingerprint = fingerprint
        self.phases = dict(phases or {})  # name -> {"completed_at_ms": int}

    @classmethod
    def fresh(cls, params) -> "BenchState":
        return cls(bench_state_path(params), params_fingerprint(params))

    @classmethod
    def load(cls, params) -> "BenchState":
        """Resume state from disk; a missing file resumes from nothing
        (equivalent to a fresh run), a config mismatch is a loud error."""
        path = bench_state_path(params)
        fp = params_fingerprint(params)
        # the state file may live on remote storage (it sits next to the
        # metrics report) — route existence + read through the fs seam
        if is_remote(path):
            fs, p = get_fs(path)
            exists = fs.exists(p)
        else:
            exists = os.path.exists(path)
        if not exists:
            print(f"resume: no checkpoint at {path}; starting fresh")
            return cls(path, fp)
        with fs_open(path) as f:
            raw = json.load(f)
        if raw.get("params_fingerprint") != fp:
            raise ValueError(
                f"resume: checkpoint {path} was written by a different "
                f"bench config (fingerprint {raw.get('params_fingerprint')} "
                f"!= {fp}); delete it or fix the config"
            )
        done = sorted(raw.get("phases", {}))
        print(f"resume: checkpoint {path} has completed phases: {done}")
        return cls(path, fp, raw.get("phases"))

    def is_done(self, phase: str) -> bool:
        return phase in self.phases

    def mark_done(self, phase: str):
        self.phases[phase] = {"completed_at_ms": int(time.time() * 1000)}
        self._write()

    def _write(self):
        with fs_open_atomic(self.path, "w") as f:
            json.dump(
                {
                    "params_fingerprint": self.fingerprint,
                    "phases": self.phases,
                },
                f,
                indent=2,
            )


# ---------------------------------------------------------------------------
# phase runners (each a process boundary, like the reference's spark-submit)
# ---------------------------------------------------------------------------


def _run(cmd):
    print("====== " + " ".join(str(c) for c in cmd) + " ======", flush=True)
    subprocess.run([str(c) for c in cmd], check=True)


def run_data_gen(params, num_streams):
    cfg = params["data_gen"]

    def gen(data_dir, extra):
        _run([
            sys.executable, "-m", "nds_tpu.cli.gen_data", "local",
            "--scale", cfg["scale_factor"],
            "--parallel", cfg["parallel"],
            "--data_dir", data_dir,
            "--overwrite_output",
        ] + extra)

    gen(cfg["raw_data_path"], [])
    # one refresh set per non-power stream (maintenance phases consume them)
    for i in range(1, num_streams):
        gen(cfg["raw_data_path"] + f"_update{i}", ["--update", i])


def run_load_test(params):
    cfg = params["load_test"]
    cmd = [
        sys.executable, "-m", "nds_tpu.cli.transcode",
        params["data_gen"]["raw_data_path"],
        cfg["output_path"],
        cfg["report_path"],
        "--output_format", cfg.get("warehouse_format", "lakehouse"),
        "--output_mode", "overwrite",
    ]
    _run(cmd)


def gen_streams(params, num_streams, rngseed):
    cfg = params["generate_query_stream"]
    cmd = [
        sys.executable, "-m", "nds_tpu.cli.gen_query_stream",
        "--output_dir", cfg["stream_output_path"],
        "--streams", num_streams,
        "--scale", params["data_gen"]["scale_factor"],
        "--rngseed", rngseed,
    ]
    if cfg.get("query_template_dir"):
        cmd += ["--template_dir", cfg["query_template_dir"]]
    _run(cmd)


def power_test(params):
    cfg = params["power_test"]
    stream_dir = params["generate_query_stream"]["stream_output_path"]
    cmd = [
        sys.executable, "-m", "nds_tpu.cli.power",
        params["load_test"]["output_path"],
        os.path.join(stream_dir, "query_0.sql"),
        cfg["report_path"],
        "--input_format", params["load_test"].get("warehouse_format", "lakehouse"),
    ]
    if cfg.get("property_path"):
        cmd += ["--property_file", cfg["property_path"]]
    if cfg.get("output_path"):
        cmd += ["--output_prefix", cfg["output_path"]]
    if cfg.get("sub_queries"):
        cmd += ["--sub_queries", cfg["sub_queries"]]
    _run(cmd)


def throughput_test(params, num_streams, first_or_second):
    cfg = params["throughput_test"]
    stream_dir = params["generate_query_stream"]["stream_output_path"]
    cmd = [
        sys.executable, "-m", "nds_tpu.cli.throughput",
        params["load_test"]["output_path"],
        stream_dir,
        get_throughput_stream_nums(num_streams, first_or_second),
        cfg["report_base_path"],
        "--input_format", params["load_test"].get("warehouse_format", "lakehouse"),
    ]
    if cfg.get("mode"):
        cmd += ["--mode", cfg["mode"]]
    if cfg.get("sub_queries"):
        cmd += ["--sub_queries", cfg["sub_queries"]]
    _run(cmd)


def maintenance_test(params, num_streams, first_or_second):
    cfg = params["maintenance_test"]
    for i in get_stream_range(num_streams, first_or_second):
        refresh_dir = params["data_gen"]["raw_data_path"] + f"_update{i}"
        cmd = [
            sys.executable, "-m", "nds_tpu.cli.maintenance",
            params["load_test"]["output_path"],
            refresh_dir,
            cfg["maintenance_report_base_path"] + f"_{i}.csv",
        ]
        if cfg.get("maintenance_queries"):
            cmd += ["--maintenance_queries", cfg["maintenance_queries"]]
        _run(cmd)


def maintenance_under_load_test(params, num_streams):
    """Opt-in robustness phase: re-run stream 1's queries while the first
    refresh set's DM_* functions (and a lease-respecting vacuum) commit
    against the same warehouse — maintenance throughput x query p99
    degradation (cli.maintenance --under_load_stream). Re-applying update
    set 1 is safe: inserts append new snapshots, deletes ride ranged
    predicates, and the phase runs after every timed TPC phase."""
    cfg = params.get("maintenance_under_load") or {}
    dm_cfg = params["maintenance_test"]
    stream_dir = params["generate_query_stream"]["stream_output_path"]
    report_base = dm_cfg["maintenance_report_base_path"]
    cmd = [
        sys.executable, "-m", "nds_tpu.cli.maintenance",
        params["load_test"]["output_path"],
        params["data_gen"]["raw_data_path"] + "_update1",
        report_base + "_under_load.csv",
        "--under_load_stream", os.path.join(stream_dir, "query_1.sql"),
        "--under_load_report",
        cfg.get("report_path") or report_base + "_under_load.json",
    ]
    if cfg.get("maintenance_queries") or dm_cfg.get("maintenance_queries"):
        cmd += [
            "--maintenance_queries",
            cfg.get("maintenance_queries")
            or dm_cfg.get("maintenance_queries"),
        ]
    if cfg.get("sub_queries"):
        cmd += ["--under_load_queries", cfg["sub_queries"]]
    _run(cmd)


# ---------------------------------------------------------------------------
# phase execution with checkpointing + classified bounded retries
# ---------------------------------------------------------------------------


class PhaseError(RuntimeError):
    """A benchmark phase failed terminally (retry budget exhausted or the
    failure classified as deterministic)."""

    def __init__(self, phase, kind, attempts, cause):
        super().__init__(
            f"phase {phase} failed ({kind}) after {attempts} attempt(s): "
            f"{cause}"
        )
        self.phase = phase
        self.kind = kind


def _bench_trace_conf(params):
    """Engine conf for trace-dir resolution: the power_test property file
    is the one phase config that carries engine.* keys, so a conf-only
    `engine.trace_dir` set there still lights up orchestrator-level phase
    events and subprocess failure classification (env NDS_TRACE_DIR wins
    either way inside resolve_trace_dir)."""
    prop = (params.get("power_test") or {}).get("property_path")
    if not prop:
        return None
    try:
        from .power import load_properties

        return load_properties(prop)
    except OSError:
        return None


def _phase_failure_kind(exc, trace_dir, pre_existing, launch=None) -> str:
    """Classify a phase failure; when the exception itself is opaque (a
    subprocess CalledProcessError carries only the exit code) fall back to
    the event files the phase's child processes wrote before dying — the
    "classify subprocess phase failures from their logs" ROADMAP gap.

    `launch` is the phase's launch record ({"trace_id", "ts_ms"}): a
    candidate file's `trace_meta` must belong to THIS phase's context
    (its trace_id, or a child parented to it) or at least postdate the
    launch — a leftover file from an unrelated process (or a recycled
    pid) no longer gets blamed for this phase's death."""
    kind = faults.classify(exc)
    if kind != faults.UNKNOWN or not trace_dir:
        return kind
    new = [
        f
        for f in obs_reader.discover_event_files(trace_dir)
        if f not in pre_existing
    ]
    if launch:
        verified = []
        for f in new:
            meta = obs_reader.trace_meta_of(f)
            if meta is None:
                continue
            tid = launch.get("trace_id")
            if tid and meta.get("trace_id") is not None:
                if meta["trace_id"] == tid or meta.get("parent") == tid:
                    verified.append(f)
                continue
            if obs_reader.meta_matches_launch(
                meta, launch_ts_ms=launch.get("ts_ms")
            ):
                verified.append(f)
        new = verified
    if not new:
        return kind
    from_events = obs_reader.failure_kind_from_files(new)
    return from_events or kind


def _run_phase(state: BenchState, name: str, skip, fn, tracer=None,
               trace_dir=None):
    """Run one phase with checkpointing and bounded transient retries.

    Phase CLIs are rerun-idempotent (they overwrite their outputs), so a
    classified-transient failure retries up to NDS_PHASE_RETRIES times
    with backoff. Deterministic failures raise immediately; an
    unclassifiable subprocess exit is first re-classified from the event
    files its children wrote (NDS_TRACE_DIR), so e.g. a child that died
    mid-stream on transient IO retries while a planner bug fails fast
    (NDS_PHASE_RETRY_UNKNOWN=1 still opts genuinely-opaque exits into
    retries). An injected crash (BaseException) sails through: the process
    dies with the checkpoint recording every phase completed before it."""
    if skip or state.is_done(name):
        why = "config" if skip else "checkpoint"
        print(f"====== phase {name}: skipped ({why}) ======", flush=True)
        if tracer is not None:
            tracer.emit("phase", phase=name, event="end", status="skipped",
                        reason=why)
        return
    retries = int(os.environ.get("NDS_PHASE_RETRIES", "1"))
    retry_unknown = os.environ.get("NDS_PHASE_RETRY_UNKNOWN") == "1"
    base = float(os.environ.get("NDS_PHASE_BACKOFF", "1.0"))
    delays = faults.backoff_delays(retries, base)
    if trace_dir is None:
        trace_dir = obs_trace.resolve_trace_dir()
    # per-phase trace context: minted as a child of the orchestrator's and
    # exported through the environment so every subprocess this phase
    # spawns (power CLI, throughput parent -> its stream children, ...)
    # adopts/parents to it — the failure classifier then verifies candidate
    # event files against THIS launch record instead of trusting pids.
    # Phases run sequentially, so the env mutation cannot race a sibling.
    parent_ctx = (
        getattr(tracer, "context", None)
        or obs_trace.resolve_trace_context("full_bench")
    )
    phase_ctx = parent_ctx.child(name)
    prev_env_ctx = os.environ.get(obs_trace.TRACE_CONTEXT_ENV)
    os.environ[obs_trace.TRACE_CONTEXT_ENV] = phase_ctx.to_env_value()
    attempt = 0
    t0 = time.perf_counter()
    if tracer is not None:
        # index/total ride the begin event so the live /statusz phase view
        # (obs/metrics.py) can render orchestrator progress ("power_test,
        # 4/8") without knowing the phase plan
        idx = PHASES.index(name) + 1 if name in PHASES else None
        tracer.emit(
            "phase", phase=name, event="begin",
            **({"index": idx, "total": len(PHASES)} if idx else {}),
        )
    try:
        while True:
            attempt += 1
            launch = {
                "trace_id": phase_ctx.trace_id,
                "ts_ms": int(time.time() * 1000),
            }
            pre_existing = set(obs_reader.discover_event_files(trace_dir))
            try:
                faults.maybe_fire(name)
                fn()
                break
            except Exception as exc:
                kind = _phase_failure_kind(
                    exc, trace_dir, pre_existing, launch=launch
                )
                transient = kind in faults.RETRYABLE or (
                    kind == faults.UNKNOWN and retry_unknown
                )
                delay = next(delays, None) if transient else None
                if delay is None:
                    if tracer is not None:
                        tracer.emit(
                            "phase", phase=name, event="end", status="failed",
                            failure_kind=kind, attempts=attempt,
                            dur_ms=round(
                                (time.perf_counter() - t0) * 1000, 3
                            ),
                        )
                    raise PhaseError(name, kind, attempt, exc) from exc
                print(
                    f"====== phase {name}: attempt {attempt} failed "
                    f"({kind}: {exc}); retrying in {delay:.1f}s ======",
                    flush=True,
                )
                time.sleep(delay)
    finally:
        # restore the orchestrator-level context for the next phase (and
        # for anything the caller spawns after us)
        if prev_env_ctx is None:
            os.environ.pop(obs_trace.TRACE_CONTEXT_ENV, None)
        else:
            os.environ[obs_trace.TRACE_CONTEXT_ENV] = prev_env_ctx
    if tracer is not None:
        tracer.emit(
            "phase", phase=name, event="end", status="ok", attempts=attempt,
            dur_ms=round((time.perf_counter() - t0) * 1000, 3),
        )
    state.mark_done(name)


def run_full_bench(params, resume: bool = False):
    num_streams = params["generate_query_stream"]["num_streams"]
    if num_streams % 2 == 0 or num_streams < 3:
        raise ValueError(
            f"num_streams must be odd and >= 3 (power stream + 2 equal "
            f"non-empty throughput sets; Spec 4.3.2 wants 2*S+1, S>=4), "
            f"got {num_streams}"
        )
    faults.install_from_env()  # arm orchestrator-level injection sites
    # orchestrator event log: per-phase begin/end events, orchestrator-level
    # fault injections via the thread-local binding, and the trace dir the
    # phase-failure classifier scans for child event files. Resolution:
    # NDS_TRACE_DIR env, else engine.trace_dir from the power_test property
    # file (the one phase config carrying engine.* keys); subprocesses
    # inherit the env and write their own event files either way.
    trace_conf = _bench_trace_conf(params)
    tracer = obs_trace.tracer_from_conf(trace_conf)
    trace_dir = obs_trace.resolve_trace_dir(trace_conf)
    try:
        with obs_trace.bind(tracer):
            return _run_full_bench_phases(
                params, resume, num_streams, tracer, trace_dir
            )
    finally:
        if tracer is not None:
            tracer.close()


def _run_full_bench_phases(params, resume, num_streams, tracer, trace_dir):
    state = BenchState.load(params) if resume else BenchState.fresh(params)
    sq = num_streams // 2  # streams per Throughput Test
    _run_phase(
        state, "data_gen", params["data_gen"].get("skip"),
        lambda: run_data_gen(params, num_streams), tracer=tracer, trace_dir=trace_dir,
    )
    _run_phase(
        state, "load_test", params["load_test"].get("skip"),
        lambda: run_load_test(params), tracer=tracer, trace_dir=trace_dir,
    )
    load_report = params["load_test"]["report_path"]
    tload = get_load_time(load_report)
    _run_phase(
        state, "gen_streams", params["generate_query_stream"].get("skip"),
        lambda: gen_streams(
            params, num_streams, get_load_end_timestamp(load_report)
        ),
        tracer=tracer, trace_dir=trace_dir,
    )
    _run_phase(
        state, "power_test", params["power_test"].get("skip"),
        lambda: power_test(params), tracer=tracer, trace_dir=trace_dir,
    )
    tpower = get_power_time(params["power_test"]["report_path"])
    tt_cfg = params["throughput_test"]
    dm_cfg = params["maintenance_test"]
    _run_phase(
        state, "throughput_test_1", tt_cfg.get("skip"),
        lambda: throughput_test(params, num_streams, 1), tracer=tracer, trace_dir=trace_dir,
    )
    ttt1 = get_throughput_time(tt_cfg["report_base_path"], num_streams, 1)
    _run_phase(
        state, "maintenance_test_1", dm_cfg.get("skip"),
        lambda: maintenance_test(params, num_streams, 1), tracer=tracer, trace_dir=trace_dir,
    )
    tdm1 = get_maintenance_time(
        dm_cfg["maintenance_report_base_path"], num_streams, 1
    )
    _run_phase(
        state, "throughput_test_2", tt_cfg.get("skip"),
        lambda: throughput_test(params, num_streams, 2), tracer=tracer, trace_dir=trace_dir,
    )
    ttt2 = get_throughput_time(tt_cfg["report_base_path"], num_streams, 2)
    _run_phase(
        state, "maintenance_test_2", dm_cfg.get("skip"),
        lambda: maintenance_test(params, num_streams, 2), tracer=tracer, trace_dir=trace_dir,
    )
    tdm2 = get_maintenance_time(
        dm_cfg["maintenance_report_base_path"], num_streams, 2
    )
    # opt-in (off by default): maintenance-under-load runs only when the
    # config section explicitly enables it, and after every timed phase.
    # FAIL-SOFT: it is a diagnostics phase — its failure must not cost
    # the composite metric every timed phase already earned.
    mul_cfg = params.get("maintenance_under_load") or {}
    mul_error = None
    try:
        _run_phase(
            state, "maintenance_under_load", not mul_cfg.get("enabled"),
            lambda: maintenance_under_load_test(params, num_streams),
            tracer=tracer, trace_dir=trace_dir,
        )
    except PhaseError as exc:
        mul_error = str(exc)
        print(f"====== maintenance_under_load failed (metric unaffected): "
              f"{exc} ======", flush=True)
    metric = get_perf_metric(
        params["data_gen"]["scale_factor"], sq,
        tload, tpower, ttt1, ttt2, tdm1, tdm2,
    )
    metrics = {
        "scale_factor": params["data_gen"]["scale_factor"],
        "num_streams": num_streams,
        "Tload": tload,
        "Tpower": tpower,
        "Ttt1": ttt1,
        "Ttt2": ttt2,
        "Tdm1": tdm1,
        "Tdm2": tdm2,
        "perf_metric": metric,
    }
    if mul_error:
        metrics["maintenance_under_load_error"] = mul_error
    # budgeter-accuracy headline beside the composite metric: the bench
    # trace dir aggregates every phase subprocess's plan_feedback events.
    # FAIL-SOFT — a torn trace file must not cost a finished benchmark.
    if trace_dir:
        errs = []

        def _collect(events):
            errs.extend(
                float(e["abs_log_err"]) for e in events
                if e.get("kind") == "plan_feedback"
                and e.get("abs_log_err") is not None
            )

        try:
            prof = obs_reader.load_profile(
                [trace_dir], strict=False, events_hook=_collect
            )
            rate = obs_reader.feedback_hit_rate(prof)
            metrics["feedback_hit_rate"] = (
                None if rate is None else round(rate, 4)
            )
            errs.sort()
            metrics["budget_err_median"] = (
                round(errs[len(errs) // 2], 4) if errs else None
            )
        except Exception:
            metrics["feedback_hit_rate"] = None
            metrics["budget_err_median"] = None
    print(metrics)
    write_metrics_report(params["metrics_report_path"], metrics)
    return metrics
