-- LF_I: inventory refresh (TPC-DS spec 5.3.11).
-- Reference behavior: nds/data_maintenance/LF_I.sql.
drop view if exists iv;
create temp view iv as
select d_date_sk inv_date_sk,
       i_item_sk inv_item_sk,
       w_warehouse_sk inv_warehouse_sk,
       invn_qty_on_hand inv_quantity_on_hand
from s_inventory
left outer join warehouse on (invn_warehouse_id = w_warehouse_id)
left outer join item on (invn_item_id = i_item_id and i_rec_end_date is null)
left outer join date_dim on (d_date = invn_date);
insert into inventory (select * from iv order by inv_date_sk);
