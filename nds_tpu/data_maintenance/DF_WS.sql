-- DF_WS: web channel delete (TPC-DS spec 5.3.11.1).
-- Reference behavior: nds/data_maintenance/DF_WS.sql:30-33.
delete from web_returns where wr_order_number in
  (select distinct ws_order_number from web_sales, date_dim
   where ws_sold_date_sk = d_date_sk and d_date between date 'DATE1' and date 'DATE2');
delete from web_sales
 where ws_sold_date_sk >= (select min(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2')
   and ws_sold_date_sk <= (select max(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2');
