-- DF_I: inventory delete (TPC-DS spec 5.3.11.2). Dates come from the
-- generated `inventory_delete` table.
-- Reference behavior: nds/data_maintenance/DF_I.sql:30-32.
delete from inventory
 where inv_date_sk >= (select min(d_date_sk) from date_dim
                       where d_date between date 'DATE1' and date 'DATE2')
   and inv_date_sk <= (select max(d_date_sk) from date_dim
                       where d_date between date 'DATE1' and date 'DATE2');
