-- LF_CS: catalog_sales refresh (TPC-DS spec 5.3.11).
-- Reference behavior: nds/data_maintenance/LF_CS.sql.
drop view if exists csv;
create temp view csv as
select d1.d_date_sk cs_sold_date_sk,
       t_time_sk cs_sold_time_sk,
       d2.d_date_sk cs_ship_date_sk,
       c1.c_customer_sk cs_bill_customer_sk,
       c1.c_current_cdemo_sk cs_bill_cdemo_sk,
       c1.c_current_hdemo_sk cs_bill_hdemo_sk,
       c1.c_current_addr_sk cs_bill_addr_sk,
       c2.c_customer_sk cs_ship_customer_sk,
       c2.c_current_cdemo_sk cs_ship_cdemo_sk,
       c2.c_current_hdemo_sk cs_ship_hdemo_sk,
       c2.c_current_addr_sk cs_ship_addr_sk,
       cc_call_center_sk cs_call_center_sk,
       cp_catalog_page_sk cs_catalog_page_sk,
       sm_ship_mode_sk cs_ship_mode_sk,
       w_warehouse_sk cs_warehouse_sk,
       i_item_sk cs_item_sk,
       p_promo_sk cs_promo_sk,
       cord_order_id cs_order_number,
       clin_quantity cs_quantity,
       i_wholesale_cost cs_wholesale_cost,
       i_current_price cs_list_price,
       clin_sales_price cs_sales_price,
       (i_current_price - clin_sales_price) * clin_quantity cs_ext_discount_amt,
       clin_sales_price * clin_quantity cs_ext_sales_price,
       i_wholesale_cost * clin_quantity cs_ext_wholesale_cost,
       i_current_price * clin_quantity cs_ext_list_price,
       i_current_price * cc_tax_percentage cs_ext_tax,
       clin_coupon_amt cs_coupon_amt,
       clin_ship_cost * clin_quantity cs_ext_ship_cost,
       (clin_sales_price * clin_quantity) - clin_coupon_amt cs_net_paid,
       ((clin_sales_price * clin_quantity) - clin_coupon_amt) * (1 + cc_tax_percentage) cs_net_paid_inc_tax,
       (clin_sales_price * clin_quantity) - clin_coupon_amt + (clin_ship_cost * clin_quantity) cs_net_paid_inc_ship,
       (clin_sales_price * clin_quantity) - clin_coupon_amt + (clin_ship_cost * clin_quantity)
         + i_current_price * cc_tax_percentage cs_net_paid_inc_ship_tax,
       ((clin_sales_price * clin_quantity) - clin_coupon_amt) - (clin_quantity * i_wholesale_cost) cs_net_profit
from s_catalog_order
left outer join date_dim d1 on (cast(cord_order_date as date) = d1.d_date)
left outer join time_dim on (cord_order_time = t_time)
left outer join customer c1 on (cord_bill_customer_id = c1.c_customer_id)
left outer join customer c2 on (cord_ship_customer_id = c2.c_customer_id)
left outer join call_center on (cord_call_center_id = cc_call_center_id and cc_rec_end_date is null)
left outer join ship_mode on (cord_ship_mode_id = sm_ship_mode_id)
join s_catalog_order_lineitem on (cord_order_id = clin_order_id)
left outer join date_dim d2 on (cast(clin_ship_date as date) = d2.d_date)
left outer join catalog_page on (clin_catalog_page_number = cp_catalog_page_number
                                 and clin_catalog_number = cp_catalog_number)
left outer join warehouse on (clin_warehouse_id = w_warehouse_id)
left outer join item on (clin_item_id = i_item_id and i_rec_end_date is null)
left outer join promotion on (clin_promotion_id = p_promo_id);
insert into catalog_sales (select * from csv order by cs_sold_date_sk);
