-- LF_WS: web_sales refresh (TPC-DS spec 5.3.11).
-- Reference behavior: nds/data_maintenance/LF_WS.sql.
drop view if exists wsv;
create temp view wsv as
select d1.d_date_sk ws_sold_date_sk,
       t_time_sk ws_sold_time_sk,
       d2.d_date_sk ws_ship_date_sk,
       i_item_sk ws_item_sk,
       c1.c_customer_sk ws_bill_customer_sk,
       c1.c_current_cdemo_sk ws_bill_cdemo_sk,
       c1.c_current_hdemo_sk ws_bill_hdemo_sk,
       c1.c_current_addr_sk ws_bill_addr_sk,
       c2.c_customer_sk ws_ship_customer_sk,
       c2.c_current_cdemo_sk ws_ship_cdemo_sk,
       c2.c_current_hdemo_sk ws_ship_hdemo_sk,
       c2.c_current_addr_sk ws_ship_addr_sk,
       wp_web_page_sk ws_web_page_sk,
       web_site_sk ws_web_site_sk,
       sm_ship_mode_sk ws_ship_mode_sk,
       w_warehouse_sk ws_warehouse_sk,
       p_promo_sk ws_promo_sk,
       word_order_id ws_order_number,
       wlin_quantity ws_quantity,
       i_wholesale_cost ws_wholesale_cost,
       i_current_price ws_list_price,
       wlin_sales_price ws_sales_price,
       (i_current_price - wlin_sales_price) * wlin_quantity ws_ext_discount_amt,
       wlin_sales_price * wlin_quantity ws_ext_sales_price,
       i_wholesale_cost * wlin_quantity ws_ext_wholesale_cost,
       i_current_price * wlin_quantity ws_ext_list_price,
       i_current_price * web_tax_percentage ws_ext_tax,
       wlin_coupon_amt ws_coupon_amt,
       wlin_ship_cost * wlin_quantity ws_ext_ship_cost,
       (wlin_sales_price * wlin_quantity) - wlin_coupon_amt ws_net_paid,
       ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt) * (1 + web_tax_percentage) ws_net_paid_inc_tax,
       ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt) - (wlin_quantity * i_wholesale_cost) ws_net_paid_inc_ship,
       (wlin_sales_price * wlin_quantity) - wlin_coupon_amt + (wlin_ship_cost * wlin_quantity)
         + i_current_price * web_tax_percentage ws_net_paid_inc_ship_tax,
       ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt) - (i_wholesale_cost * wlin_quantity) ws_net_profit
from s_web_order
left outer join date_dim d1 on (cast(word_order_date as date) = d1.d_date)
left outer join time_dim on (word_order_time = t_time)
left outer join customer c1 on (word_bill_customer_id = c1.c_customer_id)
left outer join customer c2 on (word_ship_customer_id = c2.c_customer_id)
left outer join web_site on (word_web_site_id = web_site_id and web_rec_end_date is null)
left outer join ship_mode on (word_ship_mode_id = sm_ship_mode_id)
join s_web_order_lineitem on (word_order_id = wlin_order_id)
left outer join date_dim d2 on (cast(wlin_ship_date as date) = d2.d_date)
left outer join item on (wlin_item_id = i_item_id and i_rec_end_date is null)
left outer join web_page on (wlin_web_page_id = wp_web_page_id and wp_rec_end_date is null)
left outer join warehouse on (wlin_warehouse_id = w_warehouse_id)
left outer join promotion on (wlin_promotion_id = p_promo_id);
insert into web_sales (select * from wsv order by ws_sold_date_sk);
