-- DF_CS: catalog channel delete (TPC-DS spec 5.3.11.1).
-- Reference behavior: nds/data_maintenance/DF_CS.sql:30-33.
delete from catalog_returns where cr_order_number in
  (select distinct cs_order_number from catalog_sales, date_dim
   where cs_sold_date_sk = d_date_sk and d_date between date 'DATE1' and date 'DATE2');
delete from catalog_sales
 where cs_sold_date_sk >= (select min(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2')
   and cs_sold_date_sk <= (select max(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2');
