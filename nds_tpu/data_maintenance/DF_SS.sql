-- DF_SS: store channel delete (TPC-DS spec 5.3.11.1). DATE1/DATE2 are
-- substituted from the generated `delete` table at run time.
-- Reference behavior: nds/data_maintenance/DF_SS.sql:30-33.
delete from store_returns where sr_ticket_number in
  (select distinct ss_ticket_number from store_sales, date_dim
   where ss_sold_date_sk = d_date_sk and d_date between date 'DATE1' and date 'DATE2');
delete from store_sales
 where ss_sold_date_sk >= (select min(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2')
   and ss_sold_date_sk <= (select max(d_date_sk) from date_dim
                           where d_date between date 'DATE1' and date 'DATE2');
