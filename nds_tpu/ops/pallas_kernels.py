"""Pallas TPU kernels: MXU-native segment aggregation.

`jax.ops.segment_sum` over a low-cardinality group domain lowers to an XLA
scatter-add, and TPU scatters serialize on conflicting indices — the classic
TPU weakness for groupby. The MXU-native formulation instead processes a tile
of rows at a time: build the tile's one-hot group matrix in VMEM and fold the
whole aggregation into one (8 x T) @ (T x G) matmul per tile — systolic-array
work, with the one-hot never touching HBM. Row 0 of the left matrix carries
the measure, row 1 carries ones, so a single dot yields both per-group sums
and counts.

This is the TPU-first counterpart of the hash-based groupby the reference
delegates to cuDF on GPUs (reference: nds/power_run_gpu.template:20-41
configures it; the kernel itself lives in the external RAPIDS engine).

Numerics: accumulation is float32. Per-tile dot products are exact for unit
counts (T <= 2**18 rows/tile) and for measures with <= 24 significant bits;
cross-tile accumulation is float32 pairwise within the systolic array. Use
for float measures (the --floats mode of the reference) and counts; exact
int64/decimal sums stay on the scatter path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

ROW_TILE = 2048     # fact rows per grid step
GROUP_TILE = 512    # group columns per grid step (VMEM: one-hot 4 MB f32)


def _seg_kernel(group_tile: int, vals_ref, gid_ref, out_ref):
    j = pl.program_id(0)  # group tile (outer)
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    t = vals_ref.shape[1]
    vals = vals_ref[0, :]
    gid = gid_ref[0, :]
    base = j * group_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, group_tile), 1) + base
    onehot = (gid.reshape(t, 1) == cols).astype(jnp.float32)
    left = jnp.concatenate(
        [
            vals.reshape(1, t),
            jnp.ones((1, t), jnp.float32),
            jnp.zeros((6, t), jnp.float32),
        ]
    )
    # HIGHEST precision: the TPU MXU default multiplies f32 via bf16 passes
    # (~8 mantissa bits), which would break the "exact for measures with
    # <= 24 significant bits" contract; full-precision f32 passes keep it
    out_ref[:] += jnp.dot(
        left, onehot, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(jax.jit, static_argnames=("n_groups", "interpret"))
def segment_sums_pallas(vals, gid, n_groups: int, interpret: bool = False):
    """Per-group (sum, count) of float32 `vals` by int32 `gid` (< 0 = dead
    row; dead rows contribute to nothing). Returns (sums f32[n_groups],
    counts f32[n_groups])."""
    n = vals.shape[0]
    if n == 0:  # grid of zero steps would return the output uninitialized
        z = jnp.zeros(n_groups, jnp.float32)
        return z, z
    # lane-dim blocks must be 128-multiples for Mosaic
    t = -(-max(128, min(ROW_TILE, n)) // 128) * 128
    n_pad = -(-n // t) * t
    gt = min(GROUP_TILE, -(-n_groups // 128) * 128)
    g_pad = -(-n_groups // gt) * gt
    vals = jnp.pad(vals.astype(jnp.float32), (0, n_pad - n))
    gid = jnp.pad(gid.astype(jnp.int32), (0, n_pad - n), constant_values=-1)
    out = pl.pallas_call(
        functools.partial(_seg_kernel, gt),
        grid=(g_pad // gt, n_pad // t),
        in_specs=[
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, gt), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((8, g_pad), jnp.float32),
        interpret=interpret,
    )(vals.reshape(-1, t), gid.reshape(-1, t))
    return out[0, :n_groups], out[1, :n_groups]


def segment_sums(vals, gid, n_groups: int):
    """Dispatch: MXU one-hot matmul kernel on TPU, XLA scatter elsewhere."""
    if jax.devices()[0].platform == "tpu":
        return segment_sums_pallas(vals, gid, n_groups)
    live = gid >= 0
    safe = jnp.where(live, gid, 0)
    v = jnp.where(live, vals.astype(jnp.float32), 0.0)
    sums = jax.ops.segment_sum(v, safe, n_groups)
    counts = jax.ops.segment_sum(live.astype(jnp.float32), safe, n_groups)
    return sums, counts
