"""Pallas TPU kernels: MXU-native segment aggregation.

`jax.ops.segment_sum` over a low-cardinality group domain lowers to an XLA
scatter-add, and TPU scatters serialize on conflicting indices — the classic
TPU weakness for groupby. The MXU-native formulation instead processes a tile
of rows at a time: build the tile's one-hot group matrix in VMEM and fold the
whole aggregation into one (8 x T) @ (T x G) matmul per tile — systolic-array
work, with the one-hot never touching HBM. Row 0 of the left matrix carries
the measure, row 1 carries ones, so a single dot yields both per-group sums
and counts.

This is the TPU-first counterpart of the hash-based groupby the reference
delegates to cuDF on GPUs (reference: nds/power_run_gpu.template:20-41
configures it; the kernel itself lives in the external RAPIDS engine).

Numerics: accumulation is float32. Per-tile dot products are exact for unit
counts (T <= 2**18 rows/tile) and for measures with <= 24 significant bits;
cross-tile accumulation is float32 pairwise within the systolic array. Use
for float measures (the --floats mode of the reference) and counts; exact
int64/decimal sums stay on the scatter path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

ROW_TILE = 2048     # fact rows per grid step
GROUP_TILE = 512    # group columns per grid step (VMEM: one-hot 4 MB f32)


def _seg_kernel(group_tile: int, vals_ref, gid_ref, out_ref):
    j = pl.program_id(0)  # group tile (outer)
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    t = vals_ref.shape[1]
    vals = vals_ref[0, :]
    gid = gid_ref[0, :]
    base = j * group_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, group_tile), 1) + base
    onehot = (gid.reshape(t, 1) == cols).astype(jnp.float32)
    left = jnp.concatenate(
        [
            vals.reshape(1, t),
            jnp.ones((1, t), jnp.float32),
            jnp.zeros((6, t), jnp.float32),
        ]
    )
    # HIGHEST precision: the TPU MXU default multiplies f32 via bf16 passes
    # (~8 mantissa bits), which would break the "exact for measures with
    # <= 24 significant bits" contract; full-precision f32 passes keep it
    out_ref[:] += jnp.dot(
        left, onehot, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@functools.partial(jax.jit, static_argnames=("n_groups", "interpret"))
def segment_sums_pallas(vals, gid, n_groups: int, interpret: bool = False):
    """Per-group (sum, count) of float32 `vals` by int32 `gid` (< 0 = dead
    row; dead rows contribute to nothing). Returns (sums f32[n_groups],
    counts f32[n_groups])."""
    n = vals.shape[0]
    if n == 0:  # grid of zero steps would return the output uninitialized
        z = jnp.zeros(n_groups, jnp.float32)
        return z, z
    # lane-dim blocks must be 128-multiples for Mosaic
    t = -(-max(128, min(ROW_TILE, n)) // 128) * 128
    n_pad = -(-n // t) * t
    gt = min(GROUP_TILE, -(-n_groups // 128) * 128)
    g_pad = -(-n_groups // gt) * gt
    vals = jnp.pad(vals.astype(jnp.float32), (0, n_pad - n))
    gid = jnp.pad(gid.astype(jnp.int32), (0, n_pad - n), constant_values=-1)
    out = pl.pallas_call(
        functools.partial(_seg_kernel, gt),
        grid=(g_pad // gt, n_pad // t),
        in_specs=[
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, gt), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((8, g_pad), jnp.float32),
        interpret=interpret,
    )(vals.reshape(-1, t), gid.reshape(-1, t))
    return out[0, :n_groups], out[1, :n_groups]


def _seg_extreme_kernel(group_tile: int, is_max: bool, vals_ref, gid_ref,
                        out_ref):
    j = pl.program_id(0)  # group tile (outer)
    i = pl.program_id(1)  # row tile (inner)
    fill = jnp.float32(-jnp.inf if is_max else jnp.inf)

    @pl.when(i == 0)
    def _():
        ridx = jax.lax.broadcasted_iota(jnp.int32, out_ref.shape, 0)
        out_ref[:] = jnp.where(ridx == 0, fill, jnp.float32(0.0))

    t = vals_ref.shape[1]
    vals = vals_ref[0, :]
    gid = gid_ref[0, :]
    base = j * group_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, group_tile), 1) + base
    onehot = gid.reshape(t, 1) == cols
    masked = jnp.where(onehot, vals.reshape(t, 1), fill)
    tile_ext = (
        jnp.max(masked, axis=0) if is_max else jnp.min(masked, axis=0)
    )
    tile_cnt = jnp.sum(onehot.astype(jnp.float32), axis=0)
    cur = out_ref[:]
    ext = (
        jnp.maximum(cur[0, :], tile_ext)
        if is_max
        else jnp.minimum(cur[0, :], tile_ext)
    )
    cnt = cur[1, :] + tile_cnt
    out_ref[:] = jnp.concatenate(
        [ext.reshape(1, -1), cnt.reshape(1, -1), cur[2:, :]]
    )


@functools.partial(
    jax.jit, static_argnames=("n_groups", "is_max", "interpret")
)
def segment_extreme_pallas(vals, gid, n_groups: int, is_max: bool,
                           interpret: bool = False):
    """Per-group (min-or-max, count) of float32 `vals` by int32 `gid`
    (< 0 = dead row) — the VPU tile counterpart of segment_sums_pallas:
    each row tile builds its one-hot group mask in VMEM and folds a masked
    min/max over the tile, so the XLA scatter-min/max (which serializes on
    conflicting indices on TPU) never runs. Empty groups hold the ±inf
    identity with count 0; callers mask them via the count (the same
    sentinel contract as kernels.segment_reduce)."""
    n = vals.shape[0]
    fill = jnp.float32(-jnp.inf if is_max else jnp.inf)
    if n == 0:
        return (
            jnp.full(n_groups, fill, jnp.float32),
            jnp.zeros(n_groups, jnp.float32),
        )
    t = -(-max(128, min(ROW_TILE, n)) // 128) * 128
    n_pad = -(-n // t) * t
    gt = min(GROUP_TILE, -(-n_groups // 128) * 128)
    g_pad = -(-n_groups // gt) * gt
    vals = jnp.pad(vals.astype(jnp.float32), (0, n_pad - n))
    gid = jnp.pad(gid.astype(jnp.int32), (0, n_pad - n), constant_values=-1)
    out = pl.pallas_call(
        functools.partial(_seg_extreme_kernel, gt, is_max),
        grid=(g_pad // gt, n_pad // t),
        in_specs=[
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, gt), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((8, g_pad), jnp.float32),
        interpret=interpret,
    )(vals.reshape(-1, t), gid.reshape(-1, t))
    return out[0, :n_groups], out[1, :n_groups]


def _dense_build_kernel(domain_tile: int, slot_ref, rowid_ref, out_ref):
    j = pl.program_id(0)  # domain tile (outer)
    i = pl.program_id(1)  # row tile (inner)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    t = slot_ref.shape[1]
    slot = slot_ref[0, :]      # -1 = dead / out-of-range (never matches)
    rowid = rowid_ref[0, :]
    base = j * domain_tile
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, domain_tile), 1) + base
    onehot = slot.reshape(t, 1) == cols
    pres_tile = jnp.max(onehot.astype(jnp.int32), axis=0)
    rows_tile = jnp.max(
        jnp.where(onehot, rowid.reshape(t, 1), jnp.int32(0)), axis=0
    )
    cur = out_ref[:]
    out_ref[:] = jnp.concatenate(
        [
            jnp.maximum(cur[0, :], pres_tile).reshape(1, -1),
            jnp.maximum(cur[1, :], rows_tile).reshape(1, -1),
            cur[2:, :],
        ]
    )


@functools.partial(jax.jit, static_argnames=("table_cap", "interpret"))
def dense_build_pallas(rkey, rlive, rmin, table_cap: int,
                       interpret: bool = False):
    """Dense-domain join build tables (presence, row index per key slot) —
    the Pallas counterpart of `kernels.dense_build`, whose two scatter-max
    dispatches serialize on TPU exactly like the groupby scatters. Each
    row tile builds its one-hot slot mask in VMEM and folds presence/row
    maxima per domain tile; integer maxima, so results are EXACT (same
    contract as dense_build: build-side uniqueness is the caller's — with
    duplicates both formulations keep the max row index). Dead and
    out-of-range rows take slot -1 and never match a domain column."""
    n = rkey.shape[0]
    slot = rkey.astype(jnp.int64) - rmin
    slot = jnp.where(
        rlive & (slot >= 0) & (slot < table_cap), slot, jnp.int64(-1)
    ).astype(jnp.int32)
    if n == 0:
        return (
            jnp.zeros(table_cap, bool),
            jnp.zeros(table_cap, jnp.int32),
        )
    t = -(-max(128, min(ROW_TILE, n)) // 128) * 128
    n_pad = -(-n // t) * t
    gt = min(GROUP_TILE, -(-table_cap // 128) * 128)
    g_pad = -(-table_cap // gt) * gt
    slot = jnp.pad(slot, (0, n_pad - n), constant_values=-1)
    rowid = jnp.pad(
        jnp.arange(n, dtype=jnp.int32), (0, n_pad - n), constant_values=0
    )
    out = pl.pallas_call(
        functools.partial(_dense_build_kernel, gt),
        grid=(g_pad // gt, n_pad // t),
        in_specs=[
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
            pl.BlockSpec((1, t), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, gt), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((8, g_pad), jnp.int32),
        interpret=interpret,
    )(slot.reshape(-1, t), rowid.reshape(-1, t))
    return out[0, :table_cap] > 0, out[1, :table_cap]


#: counting-sort routing caps (exec._sort_perm_route gates on them): the
#: one-hot rank tile holds the whole (padded) domain in VMEM, and ranks
#: accumulate in f32 (exact to 2**24 — matmul counts of 0/1 entries)
SORT_ROW_TILE = 256
SORT_MAX_DOMAIN = 2048
SORT_MAX_ROWS = 1 << 24


def _sort_rank_kernel(vals_ref, rank_ref, hist_ref):
    """One row tile of the stable counting-rank: rank[r] = (# rows with
    the same key in PREVIOUS tiles) + (# earlier rows with the same key in
    THIS tile). The running per-key histogram rides the hist output block
    (revisited across the sequential grid, the same accumulation pattern
    as the segment kernels); its final state is the key histogram the
    caller turns into counting-sort offsets."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    t = vals_ref.shape[1]
    g = hist_ref.shape[1]
    vals = vals_ref[0, :]
    cols = jax.lax.broadcasted_iota(jnp.int32, (t, g), 1)
    onehot = (vals.reshape(t, 1) == cols).astype(jnp.float32)
    carry = hist_ref[0, :]
    # rank contribution from previous tiles: each row gathers its key's
    # running count via its one-hot row (a (t,g)x(g,1) matmul-gather)
    prev = jnp.dot(
        onehot, carry.reshape(g, 1),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )[:, 0]
    # within-tile stable rank: strictly-lower-triangular ones L gives
    # (L @ onehot)[r, key] = earlier same-key rows; gather own column
    rows_i = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    cols_i = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    tril = (cols_i < rows_i).astype(jnp.float32)
    la = jnp.dot(
        tril, onehot,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    within = jnp.sum(la * onehot, axis=1)
    rank_ref[0, :] = prev + within
    new_hist = carry + jnp.sum(onehot, axis=0)
    hist_ref[:] = jnp.concatenate(
        [new_hist.reshape(1, -1), jnp.zeros((7, g), jnp.float32)]
    )


@functools.partial(jax.jit, static_argnames=("domain", "interpret"))
def sort_rank_pallas(vals, domain: int, interpret: bool = False):
    """(stable within-key rank f32[n], key histogram f32[domain]) of int32
    `vals` in [0, domain); -1 marks a padded lane (contributes nothing,
    rank output unspecified). domain <= SORT_MAX_DOMAIN (the one-hot tile
    holds the whole padded domain), n <= SORT_MAX_ROWS (f32-exact
    counts)."""
    n = vals.shape[0]
    g = -(-max(domain, 128) // 128) * 128
    if n == 0:
        return jnp.zeros(0, jnp.float32), jnp.zeros(domain, jnp.float32)
    t = -(-max(128, min(SORT_ROW_TILE, n)) // 128) * 128
    n_pad = -(-n // t) * t
    vals = jnp.pad(
        vals.astype(jnp.int32), (0, n_pad - n), constant_values=-1
    )
    rank, hist = pl.pallas_call(
        _sort_rank_kernel,
        grid=(n_pad // t,),
        in_specs=[pl.BlockSpec((1, t), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((1, t), lambda i: (i, 0)),
            pl.BlockSpec((8, g), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad // t, t), jnp.float32),
            jax.ShapeDtypeStruct((8, g), jnp.float32),
        ],
        interpret=interpret,
    )(vals.reshape(-1, t))
    return rank.reshape(-1)[:n], hist[0, :domain]


@functools.partial(jax.jit, static_argnames=("domain", "interpret"))
def sort_perm_pallas(word, domain: int, interpret: bool = False):
    """Stable ascending argsort of one small-domain sort word — the
    Pallas counting-sort counterpart of the canonical kv-sort kernel
    (kernels._kv_sort_perm), for words whose packed value span fits
    SORT_MAX_DOMAIN (dictionary codes, tight date spans, the common
    TPC-DS ORDER BY shapes). Identical permutation to the canonical
    kernel by construction: both are stable ascending, and counting-sort
    position = offset[key] + stable within-key rank. XLA:TPU lax.sort
    compiles a fresh comparator kernel per operand/shape tuple and runs a
    serial bitonic network; this path is two MXU one-hot matmuls per row
    tile plus one collision-free scatter."""
    n = word.shape[0]
    vals = word.astype(jnp.int32)
    rank, hist = sort_rank_pallas(vals, domain, interpret=interpret)
    counts = hist.astype(jnp.int32)
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix
    pos = offsets[jnp.clip(vals, 0, domain - 1)] + rank.astype(jnp.int32)
    # positions are unique by construction: the scatter is collision-free
    return (
        jnp.zeros(n, jnp.int32)
        .at[pos]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )


def segment_sums(vals, gid, n_groups: int):
    """Dispatch: MXU one-hot matmul kernel on TPU, XLA scatter elsewhere."""
    if jax.devices()[0].platform == "tpu":
        return segment_sums_pallas(vals, gid, n_groups)
    live = gid >= 0
    safe = jnp.where(live, gid, 0)
    v = jnp.where(live, vals.astype(jnp.float32), 0.0)
    sums = jax.ops.segment_sum(v, safe, n_groups)
    counts = jax.ops.segment_sum(live.astype(jnp.float32), safe, n_groups)
    return sums, counts
