"""Device kernel library: the cuDF-equivalent relational primitives.

These are the hot ops the reference delegates to the external RAPIDS/cuDF
engine (reference: BASELINE.json north star; nds/power_run_gpu.template:20-41
merely configures them). Here each primitive is a `jit`-compiled JAX function
over dense padded buffers:

  - compaction (filter)          cumsum + scatter + gather
  - equi-join (inner/outer/semi/anti)  hash + sort + searchsorted + verify
  - group-by aggregation         word sort + boundary flags + segment reduce
  - order-by                     word sort with null/direction folding
  - window functions             partition sort + segment scan/reduce

Design rules (TPU/XLA-first):
  * Every output is padded to a power-of-two bucket (`columnar.bucket_cap`) so
    recompiles are bounded by O(log n) distinct shapes per kernel, not O(#ops).
  * No data-dependent shapes inside jit: live counts cross to the host once
    per kernel (`int(x.sum())`) and select the bucket for the next kernel.
  * Hash matches are *candidates only*: every join verifies real key equality
    on the matched pairs, so hash collisions can never produce wrong results.
  * EVERY ordering routes through ONE canonical stable (key, iota) kv-sort
    kernel per input cap (`sort_by_words`): XLA:TPU sort compiles cost
    ~10-12 s per comparator operand at fact shapes on a 1-core host, so
    multi-key comparisons run as stable LSD passes over int64/float64 words
    instead of one multi-operand comparator kernel per query. A leading
    live word keeps padding tails at the end.
"""

from __future__ import annotations

from functools import partial, wraps
from time import perf_counter as _perf

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import trace as _obs_trace

jax.config.update("jax_enable_x64", True)

I64 = jnp.int64
U64 = jnp.uint64


# ---------------------------------------------------------------------------
# Per-kernel dispatch timing (`kernel_span` events)
#
# Plan-node op_spans (PR 3) say WHICH operator is slow; they cannot say
# which KERNEL under it, nor whether an XLA-via-jnp formulation would lose
# to a Pallas one — the data the promotion policy needs. With kernel
# tracing on (engine.trace_kernels / NDS_TRACE_KERNELS, surfaced through
# the thread-bound Tracer's `kernel_spans` flag), every decorated kernel
# entry point below times its dispatch TO COMPLETION (block_until_ready —
# async pipelining is deliberately traded for attribution; this is a
# profiling mode) and emits one `kernel_span` event. Zero-cost when off:
# one thread-local read + None check per call. Calls made while jax is
# TRACING (a fused pipeline body re-entering segment_reduce) are skipped —
# timing abstract values is meaningless and the side effect must not bake
# into an executable.
# ---------------------------------------------------------------------------


def _ktracer():
    t = _obs_trace.current()
    if t is not None and getattr(t, "kernel_spans", False):
        return t
    return None


def _has_jax_tracer(args) -> bool:
    for a in args:
        if isinstance(a, jax.core.Tracer):
            return True
        if isinstance(a, (list, tuple)) and any(
            isinstance(x, jax.core.Tracer) for x in a
        ):
            return True
    return False


def _lead_n(args) -> int:
    """Leading input length for the event's `n` field (best effort)."""
    for a in args:
        if isinstance(a, (list, tuple)) and a:
            a = a[0]
        shape = getattr(a, "shape", None)
        if shape:
            return int(shape[0])
    return 0


def _ktraced(name):
    def deco(fn):
        @wraps(fn)
        def wrapped(*args, **kwargs):
            t = _ktracer()
            if t is None or _has_jax_tracer(args):
                return fn(*args, **kwargs)
            t0 = _perf()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            t.emit(
                "kernel_span",
                kernel=name,
                dur_ms=round((_perf() - t0) * 1000.0, 3),
                n=_lead_n(args),
            )
            return out
        return wrapped
    return deco


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------


def _splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer; good avalanche, cheap on the VPU."""
    x = x.astype(U64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15)).astype(U64)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return x


def hash_columns(cols, valids) -> jnp.ndarray:
    """Combine N key columns (+ their null flags) into one int64 hash."""
    h = jnp.uint64(0x243F6A8885A308D3)
    for data, valid in zip(cols, valids):
        k = _splitmix64(data.astype(I64))
        if valid is not None:
            # null participates as its own distinct value
            k = jnp.where(valid, k, jnp.uint64(0xA5A5A5A5A5A5A5A5))
        h = _splitmix64(h * jnp.uint64(31) + k)
    return h.astype(I64)


# ---------------------------------------------------------------------------
# Cumulative ops
#
# XLA:TPU compile time for cumulative ops scales with the scanned-axis
# LENGTH (flat cumsum i64 at 2^20: ~16 s; cummax: ~25 s on this host). The
# blocked (recursive) form — short inner scans over a (B, T) reshape plus
# a scan of the block totals — compiles in ~1-2 s, so every engine
# cumulative routes through these. Exact for integers; float sums are
# reassociated block-wise (final-ulp differences vs a flat scan, within
# the validator's relative-epsilon contract).
# ---------------------------------------------------------------------------

_CUM_BLOCK = 512


def fast_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Exact inclusive prefix sum, compile-friendly on TPU."""
    n = x.shape[0]
    if n < 2 * _CUM_BLOCK or n % _CUM_BLOCK:
        return jnp.cumsum(x)
    b = n // _CUM_BLOCK
    y = jnp.cumsum(x.reshape(b, _CUM_BLOCK), axis=1)
    off = jnp.concatenate(
        [jnp.zeros(1, y.dtype), fast_cumsum(y[:, -1])[:-1]]
    )
    return (y + off[:, None]).reshape(-1)


def fast_cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Exact inclusive prefix max, compile-friendly on TPU."""
    n = x.shape[0]
    if n < 2 * _CUM_BLOCK or n % _CUM_BLOCK:
        return jax.lax.cummax(x)
    b = n // _CUM_BLOCK
    y = jax.lax.cummax(x.reshape(b, _CUM_BLOCK), axis=1)
    m = fast_cummax(y[:, -1])
    if jnp.issubdtype(x.dtype, jnp.integer):
        lo = jnp.full((1,), jnp.iinfo(x.dtype).min, x.dtype)
    else:
        lo = jnp.full((1,), -jnp.inf, x.dtype)
    off = jnp.concatenate([lo, m[:-1]])
    return jnp.maximum(y, off[:, None]).reshape(-1)


# ---------------------------------------------------------------------------
# Compaction (filter)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=())
def _compact_full(mask: jnp.ndarray) -> jnp.ndarray:
    """Indices of True entries, packed to the front, 0-padded, full length.

    cumsum + scatter instead of jnp.nonzero: XLA:TPU compiles this ~2-4x
    faster, and keeping the output full-length means ONE compile per input
    cap regardless of the caller's out_cap (the slice below is a trivial
    compile). With compiles costing seconds per shape on a 1-core host,
    (shape x out_cap) kernel proliferation was a top cold-start cost."""
    n = mask.shape[0]
    pos = jnp.where(mask, fast_cumsum(mask.astype(jnp.int32)) - 1, n)
    return (
        jnp.zeros(n, jnp.int32)
        .at[pos]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )


def _multi_device(x) -> bool:
    """True for a CONCRETE array actually sharded across > 1 device (mesh
    sessions). Tracers/host arrays report False — traced callers keep the
    single-device kernel choice, which is correct there by construction."""
    s = getattr(x, "sharding", None)
    if s is None:
        return False
    try:
        return len(s.device_set) > 1
    except Exception:
        return False


@partial(jax.jit, static_argnames=())
def _compact_full_sorted(mask: jnp.ndarray) -> jnp.ndarray:
    """_compact_full via the canonical kv-sort kernel: a stable ascending
    sort of (dead, index) puts live indices first in original order —
    identical output to the cumsum+scatter path (zeros past the count).

    This is the MESH-SAFE variant: jax 0.4.37's SPMD partitioner
    mislowers the blocked fast_cumsum -> where -> scatter(mode="drop")
    composition over a row-sharded mask (cross-shard scatter writes are
    dropped, so compaction silently truncates — caught by the SF0.01
    mesh-vs-oracle gate on query77/query83). The sort kernel partitions
    correctly, so sharded masks route here instead.

    Re-tested 2026-08-07 on jax 0.4.37: an 8-way forced-host-device mesh
    (xla_force_host_platform_device_count) lowers the scatter path
    correctly on CPU, so the mislowering is specific to the XLA:TPU SPMD
    pipeline and CANNOT be re-verified from this host. Keep the sorted
    route for sharded masks until the mesh-vs-oracle gate passes with it
    removed on real TPU devices."""
    n = mask.shape[0]
    perm = sort_by_words([(~mask).astype(jnp.int64)])
    count = jnp.sum(mask, dtype=jnp.int32)
    return jnp.where(
        jnp.arange(n, dtype=jnp.int32) < count, perm.astype(jnp.int32), 0
    )


@_ktraced("compact_indices")
def compact_indices(mask: jnp.ndarray, out_cap: int) -> jnp.ndarray:
    """Indices of True entries, padded with 0 to out_cap."""
    if _multi_device(mask):
        full = _compact_full_sorted(mask)
    else:
        full = _compact_full(mask)
    n = mask.shape[0]
    if out_cap <= n:
        return jax.lax.slice(full, (0,), (out_cap,))
    return jnp.pad(full, (0, out_cap - n))


def mask_count(mask: jnp.ndarray) -> int:
    return int(jnp.sum(mask))


# ---------------------------------------------------------------------------
# Sorting
# ---------------------------------------------------------------------------


# -- canonical kv sort ------------------------------------------------------
# XLA:TPU sort compile time is ~10-12 s per comparator operand at fact-table
# shapes (measured on the 1-core bench host), and every distinct
# (operand count, shapes) tuple is its own kernel. The engine therefore
# routes EVERY ordering through one canonical kernel: a stable
# (int64 key, int32 iota) sort — one compile per input cap, persisted in
# the XLA cache, reused by every sort/group/join in every query.
# Multi-word keys run as stable LSD passes over the same kernel.


@partial(jax.jit, static_argnames=())
def _kv_sort_perm(key: jnp.ndarray) -> jnp.ndarray:
    iota = jnp.arange(key.shape[0], dtype=jnp.int32)
    return jax.lax.sort((key, iota), num_keys=1, is_stable=True)[1]


def kv_sort_perm(key: jnp.ndarray) -> jnp.ndarray:
    """Stable ascending argsort of one int64 key via the canonical kernel."""
    return _kv_sort_perm(key.astype(I64))


@partial(jax.jit, static_argnames=())
def word_span(word: jnp.ndarray):
    """(min, max) over ONE sort word, padding included — the span probe
    for the Pallas counting-sort route (exec._sort_perm_route): every
    value in the word (live, dead, and null codes alike) is a legitimate
    sort key, so the span must cover them all. One fused dispatch; the
    caller pays the single host sync."""
    w = word.astype(I64)
    return jnp.stack([jnp.min(w), jnp.max(w)])


@_ktraced("sort_by_words")
def sort_by_words(words) -> jnp.ndarray:
    """Stable lexicographic argsort by a list of int64 words (most
    significant first): LSD radix over the canonical kv-sort kernel."""
    perm = None
    for w in reversed(words):
        k = w if perm is None else w[perm]
        p = _kv_sort_perm(k)
        perm = p if perm is None else perm[p]
    return perm


def float_key_words(x: jnp.ndarray):
    """Exact injective float64 -> (exponent, mantissa) int64 word pair for
    join-key equality: equal floats map to equal pairs, distinct to
    distinct. Built from frexp arithmetic because this TPU toolchain
    emulates 64-bit types and cannot compile bitcast-convert on s64.
    Spark semantics: -0.0 == 0.0 and NaN == NaN (normalized); +-inf get
    reserved exponent codes (frexp on non-finite input is undefined)."""
    x = x.astype(jnp.float64)
    x = jnp.where(x == 0.0, 0.0, x)  # -0.0 -> +0.0
    special = jnp.isnan(x) | jnp.isinf(x)
    m, e = jnp.frexp(jnp.where(special, 0.0, x))
    # m = j/2^53 with |j| in [2^52, 2^53): m * 2^53 is exactly integral,
    # so the pair (e, j) loses nothing. e in [-1073, 1024] for finite x.
    ew = e.astype(I64)
    mw = (m * jnp.float64(1 << 53)).astype(I64)
    ew = jnp.where(jnp.isnan(x), jnp.int64(99999), ew)
    ew = jnp.where(jnp.isinf(x) & (x > 0), jnp.int64(99998), ew)
    ew = jnp.where(jnp.isinf(x) & (x < 0), jnp.int64(-99999), ew)
    mw = jnp.where(special, 0, mw)
    return ew, mw


@_ktraced("group_by_words")
def group_by_words(words, live_mask, nlive=None):
    """group_rows over pre-encoded key words (exact encodings: equal words
    <=> equal keys). The word list must place live rows first (callers fold
    ~live into the leading word via the packer)."""
    order = sort_by_words(words)
    sorted_words = [w[order] for w in words]
    flags = _word_flags(sorted_words)
    gid = fast_cumsum(flags.astype(jnp.int32)) - 1
    if nlive is None:
        nlive = mask_count(live_mask)
    if nlive == 0:
        return order, gid, 0
    ngroups = int(gid[nlive - 1]) + 1
    return order, gid, ngroups


@partial(jax.jit, static_argnames=())
def _word_flags(sorted_words):
    """Group-boundary flags from adjacent word inequality."""
    n = sorted_words[0].shape[0]
    flag = jnp.zeros(n, dtype=bool).at[0].set(True)
    for w in sorted_words:
        flag = flag.at[1:].max(w[1:] != w[:-1])
    return flag


def fold_sort_key(data, valid, ascending: bool, nulls_first: bool):
    """Direction/null folding for ONE sort key: the transformed comparison
    arrays in major->minor significance order ([null_rank, value] when the
    key is nullable, else [value]). Shared by the single-device lexsort and
    the distributed samplesort (exec._try_dist_sort) so the two orderings
    can never diverge."""
    d = data
    if jnp.issubdtype(d.dtype, jnp.integer):
        d = d.astype(I64)
    if not ascending:
        d = -d
    if valid is None:
        return [d]
    null_rank = jnp.where(valid, jnp.int32(0),
                          jnp.int32(-1 if nulls_first else 1))
    return [null_rank, jnp.where(valid, d, jnp.zeros((), d.dtype))]


# -- spec-driven word building ----------------------------------------------
# Building sort words op-by-op in eager mode costs ~0.6 s of XLA compile
# per (op, shape) instance on this host — a fresh chain per query. Instead
# the whole encoding compiles as ONE function per (spec, shapes) key, and
# field widths are quantized to a small ladder so the same compiled
# encoder serves every query whose keys have similar spans.

_WIDTH_LADDER = (2, 3, 4, 6, 8, 11, 16, 22, 32, 44, 62)


def quantize_width(w: int) -> int:
    for q in _WIDTH_LADDER:
        if w <= q:
            return q
    return 63  # force standalone


@_ktraced("build_sort_words")
@partial(jax.jit, static_argnames=("spec",))
def build_sort_words(spec, live, *arrays):
    """Encode sort keys into words under a STATIC spec.

    spec: tuple of field descriptors, major->minor:
      ("L",)                 — live bit from `live` (dead rows last)
      ("i", width, asc, nf, has_valid) — bounded int field, mixed-radix
            packed; consumes data, vmin, vmax [, valid] from `arrays`
      ("I", asc, nf, has_valid)        — unbounded int, standalone word;
            consumes data [, valid]
      ("f", asc, nf, has_valid)        — float: 1-bit NaN rank into the
            shared stream + standalone f64 word; consumes data [, valid]
    Returns the word tuple for sort_by_words / group_by_words."""
    it = iter(arrays)
    words = []
    cur = {"w": None, "bits": 0}

    def flush():
        if cur["w"] is not None:
            words.append(cur["w"])
        cur["w"] = None
        cur["bits"] = 0

    def add(code, width):
        if cur["bits"] + width > 62:
            flush()
        code = code & ((1 << width) - 1)  # clamp dead-row garbage
        cur["w"] = (
            code if cur["w"] is None else (cur["w"] << width) | code
        )
        cur["bits"] += width

    for field in spec:
        kind = field[0]
        if kind == "L":
            add(jnp.where(live, 0, 1).astype(I64), 1)
            continue
        if kind == "i":
            _, width, asc, nf, hv = field
            d = next(it).astype(I64)
            vmin = next(it)
            vmax = next(it)
            v = next(it) if hv else None
            code = (d - vmin + 1) if asc else (vmax - d + 1)
            if v is not None:
                # null first -> 0; null last -> top code (clamped by add)
                code = jnp.where(v, code, 0 if nf else (1 << width) - 1)
            add(code, width)
            continue
        _, asc, nf, hv = field
        d = next(it)
        v = next(it) if hv else None
        if v is not None:
            add(jnp.where(v, 1 if nf else 0, 0 if nf else 1).astype(I64), 1)
        if kind == "I":
            w = d.astype(I64)
            if not asc:
                w = ~w
            if v is not None:
                w = jnp.where(v, w, 0)
        else:  # float
            w = d.astype(jnp.float64)
            if v is not None:
                w = jnp.where(v, w, 0.0)  # mask nulls BEFORE the NaN rank
            w = jnp.where(w == 0.0, 0.0, w)  # -0.0 == 0.0
            nan = jnp.isnan(w)
            add(jnp.where(nan, 1 if asc else 0, 0 if asc else 1).astype(I64),
                1)
            w = jnp.where(nan, 0.0, w)
            if not asc:
                w = -w
        flush()
        words.append(w)
    flush()
    return tuple(words)


def key_words(keys, live_mask):
    """Generic word encoding for (data, valid, ascending, nulls_first) key
    tuples: a leading live word (dead rows last), then per key a 1-bit
    null-rank word when nullable, a 1-bit NaN-rank word for floats (Spark:
    NaN greater than +inf), and the value word with direction folded
    (order-reversing bitwise not for ints, negation for floats). One word
    per field — the engine's Executor._sort_words builds tighter mixed-radix
    packings with bounds; this bounds-free version serves the kernel-level
    API and tests."""
    words = [jnp.where(live_mask, jnp.int64(0), jnp.int64(1))]
    for data, valid, asc, nf in keys:
        if nf is None:
            nf = asc
        if valid is not None:
            words.append(
                jnp.where(valid, 1 if nf else 0, 0 if nf else 1).astype(I64)
            )
        if jnp.issubdtype(data.dtype, jnp.floating):
            w = data.astype(jnp.float64)
            if valid is not None:
                w = jnp.where(valid, w, 0.0)
            w = jnp.where(w == 0.0, 0.0, w)  # -0.0 == 0.0
            nan = jnp.isnan(w)
            words.append(
                jnp.where(nan, 1 if asc else 0, 0 if asc else 1).astype(I64)
            )
            w = jnp.where(nan, 0.0, w)
            if not asc:
                w = -w
        else:
            w = data.astype(I64)
            if not asc:
                w = ~w
            if valid is not None:
                w = jnp.where(valid, w, 0)
        words.append(w)
    return words


def sort_indices(keys, live_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable multi-key sort; returns row order with live rows first.

    `keys` is a list of (data:int64/float64, valid:bool|None, ascending:bool,
    nulls_first:bool) in major-to-minor significance order. Runs as stable
    LSD passes over the canonical kv kernel (sort_by_words)."""
    return sort_by_words(key_words(keys, live_mask))


# ---------------------------------------------------------------------------
# Grouping (sort-based): group ids + segment reductions
# ---------------------------------------------------------------------------


def group_rows(keys, valids, live_mask, nlive=None):
    """Sort rows so equal keys are adjacent and assign group ids.

    Returns (order, gid_sorted, ngroups): `order` the sorted row order,
    `gid_sorted[i]` the 0-based group of sorted row i, `ngroups` the number of
    live groups (host int). Nulls form their own group (Spark GROUP BY
    semantics). Pass `nlive` when the live count is already known on the host
    (a Table's nrows) — it saves one device round trip per groupby."""
    tuples = [(d, v, True, True) for d, v in zip(keys, valids)]
    return group_by_words(key_words(tuples, live_mask), live_mask, nlive)


@_ktraced("segment_reduce")
@partial(jax.jit, static_argnames=("num_segments", "op"))
def segment_reduce(vals, gid, weight, num_segments, op):
    """Segment reduction with a live/validity weight mask.

    op: sum | min | max | count | sumsq
    """
    if op == "count":
        return jax.ops.segment_sum(weight.astype(I64), gid, num_segments)
    if op == "sum":
        v = jnp.where(weight, vals, jnp.zeros((), vals.dtype))
        return jax.ops.segment_sum(v, gid, num_segments)
    if op == "sumsq":
        v = jnp.where(weight, vals.astype(jnp.float64) ** 2, 0.0)
        return jax.ops.segment_sum(v, gid, num_segments)
    if op == "min":
        big = _extreme(vals.dtype, True)
        v = jnp.where(weight, vals, big)
        return jax.ops.segment_min(v, gid, num_segments)
    if op == "max":
        small = _extreme(vals.dtype, False)
        v = jnp.where(weight, vals, small)
        return jax.ops.segment_max(v, gid, num_segments)
    raise ValueError(op)


def _extreme(dtype, is_max):
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if is_max else info.min, dtype)
    return jnp.asarray(jnp.inf if is_max else -jnp.inf, dtype)


@_ktraced("segment_reduce_with_count")
@partial(jax.jit, static_argnames=("num_segments", "op"))
def segment_reduce_with_count(vals, gid, weight, num_segments, op):
    """(reduction, live count) per segment in ONE dispatch.

    Every non-count aggregate needs both — the count drives SQL
    NULL-on-empty output validity — and issuing them as two jitted calls
    paid a second dispatch and let XLA re-derive the masked operand
    instead of sharing it."""
    return (
        segment_reduce(vals, gid, weight, num_segments, op),
        segment_reduce(vals, gid, weight, num_segments, "count"),
    )


@_ktraced("batched_min_max")
def batched_min_max(datas, valids, live):
    """Masked (min, max) of several int64 columns in one dispatch batch, so
    the caller pays ONE device->host transfer regardless of column count.
    Returns stacked [k, 2]; an empty/all-null column yields (0, -1) (i.e.
    vmax < vmin) so callers can detect it."""
    info = jnp.iinfo(I64)
    outs = []
    for d, v in zip(datas, valids):
        m = live if v is None else (live & v)
        mn = jnp.min(jnp.where(m, d, info.max))
        mx = jnp.max(jnp.where(m, d, info.min))
        nonempty = m.any()
        outs.append(
            jnp.stack(
                [jnp.where(nonempty, mn, 0), jnp.where(nonempty, mx, -1)]
            )
        )
    return jnp.stack(outs)


# ---------------------------------------------------------------------------
# Equi-join
# ---------------------------------------------------------------------------


def _join_prepare(rhash, rlive):
    """Sort right-side hashes; dead rows get a reserved slot at the end.
    Eager (not jitted whole) so the sort reuses the canonical kv kernel."""
    rh = jnp.where(rlive, rhash, jnp.iinfo(I64).max)
    order = _kv_sort_perm(rh)
    return rh[order], order


@partial(jax.jit, static_argnames=())
def _join_counts(rh_sorted, lhash, llive):
    lh = jnp.where(llive, lhash, jnp.iinfo(I64).min)
    lo = jnp.searchsorted(rh_sorted, lh, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rh_sorted, lh, side="right").astype(jnp.int32)
    counts = jnp.where(llive, hi - lo, 0)
    return lo, counts


@partial(jax.jit, static_argnames=("out_cap",))
def _join_expand(lo, counts, rorder, out_cap):
    """Expand (row, count) pairs into candidate (li, ri) index pairs.

    Owner assignment is scatter + blocked prefix-max, all int32: each
    contributing row's index lands at its output-range start and cummax
    fills the range (count>0 rows have unique starts; count-0 rows park at
    out_cap and drop). The previous searchsorted over an int64
    arange(out_cap) ran ~13 s at a 16M-candidate fact join on this
    toolchain, which emulates 64-bit element types — this formulation is
    ~50 ms at the same shape."""
    counts = counts.astype(jnp.int32)
    offs = (fast_cumsum(counts) - counts).astype(jnp.int32)  # exclusive
    total = jnp.sum(counts)
    rows = jnp.arange(lo.shape[0], dtype=jnp.int32)
    starts = jnp.where(counts > 0, offs, out_cap)
    owner = jnp.full(out_cap, -1, jnp.int32).at[starts].max(rows, mode="drop")
    li = jnp.clip(fast_cummax(owner), 0, lo.shape[0] - 1)
    p = jnp.arange(out_cap, dtype=jnp.int32)
    j = p - offs[li]
    ri_sorted_pos = jnp.clip(lo[li] + j, 0, rorder.shape[0] - 1)
    ri = rorder[ri_sorted_pos]
    pair_live = p < total
    return li, ri, pair_live


@_ktraced("join_candidates")
def join_candidates(lkeys, lvalids, llive, rkeys, rvalids, rlive):
    """Hash-match candidate pairs; caller MUST verify real key equality.

    Returns (li, ri, pair_live, total_candidates). Rows with any null key
    never match (SQL equality semantics).
    """
    lh = hash_columns(lkeys, lvalids)
    rh = hash_columns(rkeys, rvalids)
    lnn = _all_valid(lvalids, llive)
    rnn = _all_valid(rvalids, rlive)
    rh_sorted, rorder = _join_prepare(rh, rnn)
    lo, counts = _join_counts(rh_sorted, lh, lnn)
    # int64 reduction + host-side guard: _join_expand's owner-assignment
    # arithmetic (exclusive cumsum, positions) runs in int32 for speed, so
    # a candidate total past 2^31 would silently wrap into garbage pair
    # indices. Fail loudly instead (such an out_cap wouldn't allocate
    # anyway; the realistic trigger is a pathological cross-join-like key).
    total = int(jnp.sum(counts, dtype=jnp.int64))
    _check_pair_count(total)
    # genuine import cycle: engine.columnar jits through ops.kernels, so a
    # module-level import here would deadlock package init; cold path
    # (sparse-join expansion sizing), one sys.modules hit per expand
    # nds-lint: disable=local-import
    from ..engine.columnar import bucket_cap

    out_cap = bucket_cap(max(total, 1))
    li, ri, pair_live = _join_expand(lo, counts, rorder, out_cap)
    return li, ri, pair_live, total


def _check_pair_count(total: int):
    """Host-side int32-range guard for join candidate expansion: the
    output capacity is the next power-of-two bucket >= total, and that cap
    itself must stay an int32 value (it is used as the parked-row sentinel
    in the owner scatter), so the largest safe bucket is 2^30."""
    if total > 1 << 30:
        raise ValueError(
            f"join candidate count {total} exceeds the int32-safe "
            f"expansion capacity (2^30); refusing to expand (the int32 "
            f"pair arithmetic would wrap silently)"
        )


def _all_valid(valids, live):
    m = live
    for v in valids:
        if v is not None:
            m = m & v
    return m


def pack_key_words(sides, bounds):
    """Pack N aligned integer key columns into one exact int64 word per
    side. `sides` is a list of column lists (one list per side, each
    [(data, valid)] of equal length N); `bounds` is [(vmin, vmax)] per key
    (host ints, union over all sides). Layout per key: (value - vmin + 1)
    in its bit field, 0 for NULL. Returns one word array per side, or None
    when the packed width exceeds 62 bits. The single definition keeps the
    catalog's PK verification and the executor's packed join bit-for-bit
    identical."""
    shift = 0
    words = [None] * len(sides)
    for ki, (vmin, vmax) in enumerate(bounds):
        span = vmax - vmin + 2  # +1 for the NULL slot
        bits = max(1, (span - 1).bit_length())
        if shift + bits > 62:
            return None
        for si, side in enumerate(sides):
            data, valid = side[ki]
            v = data.astype(I64) - vmin + 1
            if valid is not None:
                v = jnp.where(valid, v, 0)
            part = v << shift
            words[si] = part if words[si] is None else words[si] + part
        shift += bits
    return words


@_ktraced("member_lookup")
def member_lookup(lwords, lnn, rwords, rnn):
    """Exact-word membership probe: for each left row, is its packed key
    word present among live right words, and at which right row?

    Requires collision-free words (exact packing, not hashing) — presence
    needs no verification and right-side duplicates cannot hide a match
    (`ri` is then the first duplicate in sorted order; callers needing a
    unique right side must know it from plan metadata). The sort runs
    eagerly through the shared canonical kv-sort so its per-shape compile
    is amortized with every other sorting consumer."""
    big = jnp.iinfo(I64).max
    rw = jnp.where(rnn, rwords, big)
    order = _kv_sort_perm(rw)
    return _member_probe(rw[order], order, lwords, lnn)


@partial(jax.jit, static_argnames=())
def _member_probe(rw_sorted, order, lwords, lnn):
    n = rw_sorted.shape[0]
    probe = jnp.where(lnn, lwords, jnp.int64(-1))
    lo = jnp.clip(
        jnp.searchsorted(rw_sorted, probe, side="left"), 0, n - 1
    ).astype(jnp.int32)
    # packed words are non-negative, so the -1 dead-left probe never hits
    found = lnn & (rw_sorted[lo] == probe)
    ri = order[lo]
    return found, ri


@_ktraced("verify_pairs")
@partial(jax.jit, static_argnames=())
def verify_pairs(li, ri, pair_live, lkeys, lvalids, llive, rkeys, rvalids, rlive):
    """AND real key equality into the candidate mask (collision shield)."""
    ok = pair_live & llive[li] & rlive[ri]
    for (ld, lv), (rd, rv) in zip(zip(lkeys, lvalids), zip(rkeys, rvalids)):
        eq = ld[li].astype(I64) == rd[ri].astype(I64)
        if lv is not None:
            eq = eq & lv[li]
        if rv is not None:
            eq = eq & rv[ri]
        ok = ok & eq
    return ok


@partial(jax.jit, static_argnames=("cap",))
def matched_mask(li, ok, cap):
    """Per-left-row flag: does row have at least one verified match?"""
    return jnp.zeros(cap, dtype=bool).at[li].max(ok)


# ---------------------------------------------------------------------------
# Dense-domain join (star-join fast path)
#
# TPC-DS dimension tables key on dense surrogate keys, so a fact->dim join
# is a bounds-checked gather through a dense lookup table instead of a
# sort + searchsorted. This is both the single-chip hot path (no O(n log n)
# sort over the fact side) and the multi-chip one: probes are elementwise
# over row-sharded fact columns, the build side is replicated, so XLA/GSPMD
# keeps the whole probe local to each chip (the scaling-book "gather through
# replicated dim" layout).
# ---------------------------------------------------------------------------


@_ktraced("dense_build")
@partial(jax.jit, static_argnames=("table_cap",))
def dense_build(rkey, rlive, rmin, table_cap):
    """Build presence/row-index tables over the key domain
    [rmin, rmin+table_cap). Out-of-range and dead rows scatter to drop.
    Build-side uniqueness (needed by inner/left) is the caller's contract,
    established from catalog ColStats — not re-checked on device."""
    slot = jnp.where(rlive, rkey.astype(I64) - rmin, jnp.int64(table_cap))
    slot = jnp.where((slot >= 0) & (slot <= table_cap), slot, table_cap)
    presence = jnp.zeros(table_cap, bool).at[slot].max(rlive, mode="drop")
    rows = (
        jnp.zeros(table_cap, jnp.int32)
        .at[slot]
        .max(jnp.arange(rkey.shape[0], dtype=jnp.int32), mode="drop")
    )
    return presence, rows


@_ktraced("dense_probe")
@partial(jax.jit, static_argnames=("table_cap",))
def dense_probe(lkey, llive, rmin, presence, rows, table_cap):
    """Per left row: matched flag + matching right row (valid iff matched)."""
    slot = lkey.astype(I64) - rmin
    inb = (slot >= 0) & (slot < table_cap) & llive
    slot = jnp.clip(slot, 0, table_cap - 1)
    matched = inb & presence[slot]
    return matched, rows[slot]


# ---------------------------------------------------------------------------
# Direct (sort-free) grouping: domain-compressed group ids
#
# When the combined key domain is small (the TPC-DS norm: years, brand ids,
# channel flags...), the group id of every row is computed elementwise as a
# mixed-radix code and aggregation is one scatter-add per measure. No sort,
# and under GSPMD the scatter-add over row-sharded facts lowers to local
# partial aggregation + a cross-chip reduction (psum) of the small group
# table — the distributed groupby layout.
# ---------------------------------------------------------------------------


@_ktraced("direct_gid")
@partial(jax.jit, static_argnames=())
def direct_gid(keys, valids, mins, ranges, live):
    """Mixed-radix group code per row. Each key contributes
    (value - min + has_null) with code 0 reserved for NULL; dead rows get the
    all-zero code but are excluded by weight masks downstream."""
    gid = jnp.zeros(live.shape[0], I64)
    for data, valid, kmin, krange in zip(keys, valids, mins, ranges):
        code = data.astype(I64) - kmin
        if valid is not None:
            code = jnp.where(valid, code + 1, 0)
        gid = gid * krange + code
    return jnp.where(live, gid, 0)


@_ktraced("occupancy_map")
@partial(jax.jit, static_argnames=("domain_cap",))
def occupancy_map(gid, live, domain_cap):
    """occupied cell mask + dense renumbering (cell -> 0..ngroups-1)."""
    occ = jnp.zeros(domain_cap, bool).at[gid].max(live, mode="drop")
    dense = fast_cumsum(occ.astype(jnp.int32)) - 1
    return occ, dense


# ---------------------------------------------------------------------------
# Window helpers
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("num_segments",))
def segment_starts(gid, num_segments):
    """Index of the first sorted row of each segment."""
    n = gid.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.ops.segment_min(idx, gid, num_segments)


@partial(jax.jit, static_argnames=())
def running_position(gid):
    """0-based position of each sorted row within its segment.

    lax.cummax, NOT lax.associative_scan: the generic log-depth scan
    construction compiles for minutes at fact shapes on this toolchain,
    while the native cumulative ops compile like cumsum."""
    n = gid.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    first = jnp.zeros(n, dtype=bool).at[0].set(True)
    first = first.at[1:].max(gid[1:] != gid[:-1])
    start_of_own = jnp.where(first, idx, 0)
    seg_start = fast_cummax(start_of_own)
    return idx - seg_start


def value_rank(x):
    """(sorted_values, rank): each row's position in the ascending global
    sort of its value, via the canonical kv kernel. Floats sort natively
    (f64 instance; -0.0 normalized, NaN last == Spark's NaN-greatest)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        key = x.astype(jnp.float64)
        key = jnp.where(key == 0.0, 0.0, key)
    else:
        key = x.astype(I64)
    p = _kv_sort_perm(key)
    n = x.shape[0]
    rank = (
        jnp.zeros(n, jnp.int32).at[p].set(jnp.arange(n, dtype=jnp.int32))
    )
    return x[p], rank


@partial(jax.jit, static_argnames=("is_max",))
def segmented_running_extreme(vals_sorted_by_rank, rank, gid, weight,
                              is_max):
    """Running min/max within contiguous segments (gid ascending), exact
    for any dtype, without a generic associative scan (whose log-depth
    construction compiles for minutes at fact shapes on this toolchain).

    `rank`/`vals_sorted_by_rank` come from value_rank. y = gid * n + rank
    is gid-major monotone, so a native cummax over y can never leak an
    earlier segment's entry (rank < n), and mapping the winning rank back
    through the sorted values recovers the exact running extreme.
    Zero-weight rows get rank -1 (never win); a row whose segment prefix
    is all zero-weight gathers an arbitrary value — callers mask those
    via the running weight count."""
    n = jnp.int64(rank.shape[0])
    r = rank.astype(I64)
    if not is_max:
        r = n - 1 - r  # running min == running max of reversed ranks
    r = jnp.where(weight, r, -1)
    y = gid.astype(I64) * n + r
    cm = fast_cummax(y)
    win = cm - gid.astype(I64) * n
    if not is_max:
        win = n - 1 - win
    return vals_sorted_by_rank[jnp.clip(win, 0, n - 1)]
