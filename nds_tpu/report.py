"""Per-query benchmark report: timing + environment + status JSON summary.

TPU-native counterpart of the reference's PysparkBenchReport + listener chain
(reference: nds/PysparkBenchReport.py:58-119, nds/python_listener/
PythonListener.py:5-45, nds/jvm_listener/.../TaskFailureListener.scala:13-19).
Where the reference bridges Spark's JVM TaskFailureListener to Python over
py4j, our engine emits task-failure events in-process: recoverable incidents
inside the executor (e.g. a partition-exchange capacity retry on the mesh)
are fanned out to listeners registered on the Session, and a query that
completed despite such incidents is reported `CompletedWithTaskFailures`.

The summary field set and the `<prefix>-<query>-<startTime>.json` filename
contract are kept identical so downstream report tooling ports unchanged.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable

import jax

from .io.fs import fs_open

from . import __version__

_REDACTED = ("TOKEN", "SECRET", "PASSWORD", "PASSWD", "CREDENTIAL", "KEY")


def engine_conf(session) -> dict:
    """The engine's effective configuration (reference analogue: sparkConf)."""
    conf = {
        "engine.version": __version__,
        "jax.version": jax.__version__,
        "jax.backend": jax.default_backend(),
        "jax.device_count": jax.device_count(),
        "jax.devices": ", ".join(str(d) for d in jax.devices()),
        "engine.use_decimal": getattr(session, "use_decimal", True),
    }
    conf.update(getattr(session, "conf", {}) or {})
    return {k: str(v) for k, v in conf.items()}


class BenchReport:
    """Records one benchmarked callable: environment, wall-clock, status."""

    def __init__(self, session) -> None:
        self.session = session
        self.summary = {
            "env": {
                "envVars": {},
                "sparkConf": {},  # key kept for report-pipeline compatibility
                "sparkVersion": None,
            },
            "queryStatus": [],
            "exceptions": [],
            "startTime": None,
            "queryTimes": [],
        }

    def report_on(self, fn: Callable, *args, retry_oom: bool = False):
        """Run fn(*args), recording env (secrets redacted), status and time.

        retry_oom: retry ONCE after device-memory exhaustion (caller must
        guarantee fn is idempotent — read-only queries yes, DML no)."""
        env_vars = {
            k: v
            for k, v in os.environ.items()
            if not any(tag in k.upper() for tag in _REDACTED)
        }
        self.summary["env"]["envVars"] = env_vars
        self.summary["env"]["sparkConf"] = engine_conf(self.session)
        self.summary["env"]["sparkVersion"] = f"nds-tpu {__version__}"
        failures: list[str] = []
        registered = False
        try:
            self.session.register_listener(failures.append)
            registered = True
        except AttributeError:
            pass
        start_time = int(time.time() * 1000)

        def _attempt():
            # returns the error text, WITHOUT holding the exception (a live
            # traceback would pin the failed attempt's multi-GB device
            # intermediates through any recovery/retry)
            try:
                fn(*args)
                return None
            except Exception as e:
                return str(e) or type(e).__name__

        try:
            err = _attempt()
            if (
                err is not None
                and "RESOURCE_EXHAUSTED" in err
                and hasattr(self.session, "recover_memory")
            ):
                # device memory exhaustion mid-execution: drop every
                # recoverable allocation; retry once on the clean device
                # when fn is idempotent — without the recovery, one OOM
                # poisons the whole remaining stream (reference analogue:
                # executor loss -> task retry on a fresh executor)
                self.session.recover_memory("device memory exhausted")
                if retry_oom:
                    err = _attempt()
                    if err is not None and "RESOURCE_EXHAUSTED" in err:
                        self.session.recover_memory("device memory exhausted")
        finally:
            if registered:
                self.session.unregister_listener(failures.append)
        end_time = int(time.time() * 1000)
        if err is None:
            if failures:
                self.summary["queryStatus"].append("CompletedWithTaskFailures")
            else:
                self.summary["queryStatus"].append("Completed")
        else:  # a failed query must not abort the stream
            print(err)
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].append(err)
        self.summary["startTime"] = start_time
        self.summary["queryTimes"].append(end_time - start_time)
        if failures:
            self.summary["taskFailures"] = list(failures)
        return self.summary

    def write_summary(self, query_name: str, prefix: str = "") -> str:
        """Write `<prefix>-<query>-<startTime>.json` (reference keeps this
        exact name format for its Power-BI pipeline; we keep it for parity)."""
        self.summary["query"] = query_name
        filename = f"{prefix}-{query_name}-{self.summary['startTime']}.json"
        self.summary["filename"] = filename
        with fs_open(filename, "w") as f:
            json.dump(self.summary, f, indent=2)
        return filename
