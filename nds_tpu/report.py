"""Per-query benchmark report: timing + environment + status JSON summary.

TPU-native counterpart of the reference's PysparkBenchReport + listener chain
(reference: nds/PysparkBenchReport.py:58-119, nds/python_listener/
PythonListener.py:5-45, nds/jvm_listener/.../TaskFailureListener.scala:13-19).
Where the reference bridges Spark's JVM TaskFailureListener to Python over
py4j, our engine emits task-failure events in-process: recoverable incidents
inside the executor (e.g. a partition-exchange capacity retry on the mesh)
are fanned out to listeners registered on the Session, and a query that
completed despite such incidents is reported `CompletedWithTaskFailures`.

Failure domain: a failed attempt is classified (faults.classify) and walked
down a degradation ladder instead of the reference's single implicit task
retry — device OOM gets recover+retry then a shrunken blocked-union window,
transient IO gets backoff retries, a lakehouse commit conflict
(`commit_conflict`) gets bounded `commit_rebase_retry` re-runs with
jittered backoff ahead of hard failure (the aborted commit published
nothing, so the re-run is safe), a hung query is cut off by the watchdog
(`engine.query_timeout` / NDS_QUERY_TIMEOUT) and recorded as a `timeout`
failure instead of stalling the stream. Every attempt's error lands in
`exceptions`, the rungs walked land in `ladder`, and a terminal failure
carries `failureKind`.

The summary field set and the `<prefix>-<query>-<startTime>.json` filename
contract are kept identical so downstream report tooling ports unchanged.
Field-name contract: `env.sparkConf` / `env.sparkVersion` are the
compatibility keys existing report pipelines parse; `env.engineConf` /
`env.engineVersion` are first-class aliases carrying the same values —
new tooling should read the engine* names, and both are guaranteed equal.

Observability: when the session carries a tracer (NDS_TRACE_DIR /
engine.trace_dir), report_on emits a `query_span` event per benchmarked
callable (status, duration, retries, memory high-water), a `ladder_rung`
event per recovery rung, and a `watchdog_fire` event when the per-query
watchdog abandons a hung attempt; a MemorySampler records the query's
device-memory (or RSS) high-water into both the event and the summary.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

import jax

from . import faults
from .engine.spill import DEFAULT_FORCE_PARTITIONS as _SPILL_RETRY_PARTS
from .io.fs import fs_open_atomic, io_retry_budget
from .obs import trace as obs_trace
from .obs.memwatch import MemorySampler

from . import __version__

_REDACTED = ("TOKEN", "SECRET", "PASSWORD", "PASSWD", "CREDENTIAL", "KEY")

#: marker embedded in watchdog-generated error text; classify() maps it to
#: faults.TIMEOUT (keep in sync with faults._TIMEOUT_PAT)
_WATCHDOG_MARK = "query watchdog"

#: shrunken blocked-union window (rows) the last ladder rung forces when a
#: query keeps OOMing — small enough to relieve HBM pressure on any plan
#: that routes through the blocked-union path, large enough to make progress
_DEGRADED_WINDOW_ROWS = 1 << 18

#: spill_retry partition count when the budgeter recorded no static
#: recommendation (it only sizes partitions for `spill`-verdict plans):
#: engine/spill.py's DEFAULT_FORCE_PARTITIONS — the same default the
#: executor's force mode uses, imported above from the one source

#: watchdog poll slice: the deadline loop re-checks spill progress at this
#: granularity, so a timeout still fires within one slice of its budget
_WATCHDOG_POLL_S = 0.25

#: commit_rebase_retry budget + backoff: how many times the ladder
#: re-runs a transaction whose lakehouse commit aborted on an
#: overwrite/overwrite conflict (the aborted commit never published, so a
#: re-run derives its writes from the fresh head), and the jittered
#: backoff base between re-runs (two writers re-running in lockstep would
#: re-collide forever). Append/append conflicts normally converge inside
#: table._commit's own rebase loop and never reach this rung. Knobs
#: (NDS_LAKE_CONFLICT_RETRIES / NDS_LAKE_COMMIT_BACKOFF) are parsed in
#: ONE place — lakehouse/table.py — shared with maintenance's
#: statement-level retry.


def engine_conf(session) -> dict:
    """The engine's effective configuration (reference analogue: sparkConf)."""
    conf = {
        "engine.version": __version__,
        "jax.version": jax.__version__,
        "jax.backend": jax.default_backend(),
        "jax.device_count": jax.device_count(),
        "jax.devices": ", ".join(str(d) for d in jax.devices()),
        "engine.use_decimal": getattr(session, "use_decimal", True),
    }
    conf.update(getattr(session, "conf", {}) or {})
    return {k: str(v) for k, v in conf.items()}


def host_rss_watermark(session) -> int:
    """Host-RSS pre-emption watermark in bytes; 0 disables (the default).
    Conf `engine.host_rss_watermark` wins over NDS_HOST_RSS_WATERMARK.
    When the process RSS crosses it mid-query, the sampler shrinks the
    blocked-union window for the remaining windows / later queries and
    records a `host_watermark_shrink` ladder entry — recovery BEFORE the
    allocator fails, instead of after (ROADMAP carry-forward)."""
    v = getattr(session, "conf", {}).get(
        "engine.host_rss_watermark"
    ) or os.environ.get("NDS_HOST_RSS_WATERMARK")
    try:
        return max(int(v), 0) if v else 0
    except (TypeError, ValueError):
        return 0


def query_timeout(session) -> float:
    """Per-query watchdog budget in seconds; 0 disables (the default).
    Conf `engine.query_timeout` wins over the NDS_QUERY_TIMEOUT env knob."""
    v = getattr(session, "conf", {}).get("engine.query_timeout") or os.environ.get(
        "NDS_QUERY_TIMEOUT"
    )
    try:
        return max(float(v), 0.0) if v else 0.0
    except (TypeError, ValueError):
        return 0.0


class BenchReport:
    """Records one benchmarked callable: environment, wall-clock, status."""

    def __init__(self, session, tracer=None) -> None:
        self.session = session
        # `tracer` override: serve mode wraps the session tracer in a
        # per-request forwarder that labels every event with the request
        # id + tenant; everything this report (and its sampler thread)
        # emits must ride the same wrapper
        self.tracer = (
            tracer if tracer is not None
            else getattr(session, "tracer", None)
        )
        # live telemetry (obs/metrics.py): the sink learns query STARTS
        # directly (query_span only exists at the end — too late for
        # /statusz's in-flight view); everything else reaches it through
        # the tracer's emit seam
        self.sink = getattr(session, "metrics", None)
        self.summary = {
            "env": {
                "envVars": {},
                # sparkConf/sparkVersion: kept for report-pipeline
                # compatibility; engineConf/engineVersion are the
                # first-class aliases (always equal — see module docstring)
                "sparkConf": {},
                "sparkVersion": None,
                "engineConf": {},
                "engineVersion": None,
            },
            "queryStatus": [],
            "exceptions": [],
            "startTime": None,
            "queryTimes": [],
            "retries": 0,
        }
        self._name = None  # query/function label for emitted events
        self._request_id = None  # serve-mode per-request id (report_on)
        # serve-mode ladder isolation: `session.last_plan_budget` is ONE
        # field on a session that serve shares across concurrent
        # requests, so the ladder must consume the record CAPTURED at
        # this statement's plan time (Session.plan_sql), not whatever a
        # racing tenant planned last (report_on's plan_budget parameter)
        self._plan_budget_override = None

    # ------------------------------------------------------------------
    # single attempt, optionally under the watchdog
    # ------------------------------------------------------------------
    def _attempt(self, fn, args, timeout: float):
        """Run fn(*args); return None on success or the error text.

        The error is returned as TEXT, without holding the exception (a
        live traceback would pin the failed attempt's multi-GB device
        intermediates through any recovery/retry). With a timeout budget
        the attempt runs on a daemon worker thread: if the budget expires
        the worker is abandoned (it holds no locks the stream needs) and
        the query becomes a classified `timeout` failure instead of
        stalling the whole stream's Ttt window."""

        def _call():
            try:
                fn(*args)
                return None
            except faults.InjectedCrash:
                raise
            except Exception as e:
                msg = str(e)
                return f"{type(e).__name__}: {msg}" if msg else type(e).__name__

        if timeout <= 0:
            return _call()
        box = {}
        done = threading.Event()

        def _worker():
            try:
                # re-bind the session tracer: thread-locals don't inherit,
                # and session-less layers (fault registry, fs retries) find
                # their tracer through the thread-local binding
                with obs_trace.bind(self.tracer):
                    box["err"] = _call()
            except BaseException as e:  # InjectedCrash: re-raise on caller
                box["crash"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=_worker, name="nds-query-watchdog-worker", daemon=True
        )
        # arm the progress seam: a stale beat from a previous query's spill
        # phase must not extend THIS attempt's deadline
        if hasattr(self.session, "_progress_ts"):
            self.session._progress_ts = None
        t.start()
        start = time.monotonic()
        deadline = start + timeout
        fired = False
        while True:
            wait_s = min(max(deadline - time.monotonic(), 0.0),
                         _WATCHDOG_POLL_S)
            if done.wait(wait_s):
                break
            now = time.monotonic()
            if now < deadline:
                continue
            # deadline reached. A healthy out-of-core phase (external sort
            # runs, join partitions, pool merges) beats through
            # Session.spill_progress while it works; as long as the last
            # beat is younger than the budget, the attempt is slow but
            # ALIVE — re-arm one budget past the beat instead of
            # misclassifying it as a hang. A wedged query stops beating,
            # so the watchdog still fires one budget after the last beat.
            # Only beats from THIS attempt's worker thread count: an
            # abandoned previous attempt's zombie worker still beats on
            # the shared session, and honoring it would let a genuinely
            # hung next query stall the stream forever.
            prog = getattr(self.session, "_progress_ts", None)
            if (
                isinstance(prog, tuple)
                and prog[0] == t.ident
                and now - prog[1] < timeout
            ):
                deadline = prog[1] + timeout
                continue
            fired = True
            break
        if fired:
            if self.tracer is not None:
                self.tracer.emit(
                    "watchdog_fire", query=self._name, budget_s=timeout,
                    **self._rid_fields(),
                )
            return (
                f"{_WATCHDOG_MARK}: query exceeded the {timeout:.1f}s budget "
                f"(engine.query_timeout / NDS_QUERY_TIMEOUT); worker abandoned"
            )
        if "crash" in box:
            raise box["crash"]
        return box.get("err")

    # ------------------------------------------------------------------
    # degradation ladder
    # ------------------------------------------------------------------
    def _budget_prediction(self):
        """The static plan budgeter's record for the last planned
        statement when its verdict predicted memory pressure
        (analysis/budget.py sets Session.last_plan_budget), else None.
        A caller-provided record (report_on's plan_budget) wins — on a
        shared serve session the field may belong to another request."""
        rec = (
            self._plan_budget_override
            if self._plan_budget_override is not None
            else getattr(self.session, "last_plan_budget", None)
        )
        if not isinstance(rec, dict):
            return None
        if rec.get("verdict") not in ("blocked", "spill", "over", "reject"):
            return None
        return rec

    def _explicit_window(self):
        """The explicitly forced blocked-union window (conf wins over the
        NDS_UNION_AGG_WINDOW_ROWS env knob — the same resolution order
        Session.union_agg_window_rows uses), or None. Every shrink path
        must derive from THIS, not the conf knob alone: writing conf
        eclipses env, so ignoring an env-forced tiny window would let a
        'shrink' grow the effective window."""
        v = getattr(self.session, "conf", {}).get(
            "engine.union_agg_window_rows"
        ) or os.environ.get("NDS_UNION_AGG_WINDOW_ROWS")
        try:
            return int(v) if v else None
        except (TypeError, ValueError):
            return None

    def _budget_recommendation(self):
        """A window recommendation the budget_shrink rung can still APPLY:
        the prediction must carry a window (seamless over-budget plans do
        not — a knob the plan cannot consume would only waste a retry and
        pollute later statements' static sizing) and must not already be
        annotated into the plan (a blocked-verdict attempt ran the static
        window and OOM'd anyway; re-applying the identical value is
        recover_retry with extra steps). None otherwise — the ladder then
        behaves exactly as before the budgeter existed."""
        rec = self._budget_prediction()
        if rec is None or rec.get("annotated"):
            return None
        return rec.get("window_rows") or None

    def _rid_fields(self) -> dict:
        """Per-request id for emitted events ({} outside serve mode)."""
        return (
            {"request_id": self._request_id} if self._request_id else {}
        )

    def _trace_id(self):
        """The trace_id a failure bundle files under: the serve request id
        (the request IS the trace in serve mode), else the tracer's
        stamped context."""
        if self._request_id:
            return self._request_id
        ctx = getattr(self.tracer, "context", None)
        return getattr(ctx, "trace_id", None)

    def _flight_flush(self, reason: str, rungs, sampler=None):
        """Flush the process flight ring as a failure bundle for THIS
        query's incident (watchdog fire / ladder exhaustion / terminal
        failure). Best-effort by contract: forensics must never take the
        stream down, and a disabled recorder is a no-op."""
        from .obs import flight as obs_flight
        from .obs.memwatch import device_bytes_per_device, rss_bytes

        rec = obs_flight.recorder()
        if rec is None:
            return
        conf = getattr(self.session, "conf", {}) or {}
        budget = (
            self._plan_budget_override
            if self._plan_budget_override is not None
            else getattr(self.session, "last_plan_budget", None)
        )
        per_dev = device_bytes_per_device()
        memory = {
            "rss_bytes": rss_bytes(),
            "device_bytes_per_device": per_dev,
            "mem_hw_bytes": getattr(sampler, "peak_bytes", None),
            "mem_hw_per_device": getattr(sampler, "peak_per_device", None),
            "mem_source": getattr(sampler, "source", None),
        }
        rec.flush(
            reason,
            trace_id=self._trace_id(),
            query=self._name,
            budget=budget if isinstance(budget, dict) else None,
            ladder=list(rungs) if rungs else None,
            memory=memory,
            conf=conf,
            out_dir=obs_flight.resolve_flight_dir(conf),
        )

    def _next_rung(self, kind: str, rungs_taken, can_retry: bool):
        """The next recovery rung for a failure of `kind`, or None.

        device_oom: when the static budgeter predicted this plan over
        budget, the FIRST rung applies its recommendation
        (`budget_shrink`: recover + the statically derived window) instead
        of a blind recover/halve cycle; then recover_memory+retry, then
        shrink the blocked-union window (PR-1) and retry on a clean
        device; host_oom: recover+retry once; io_transient: up to
        NDS_IO_RETRIES backoff retries; timeout/planner/data/unknown:
        deterministic or likely-to-repeat — fail fast."""
        if not can_retry:
            return None
        taken = [r["rung"] for r in rungs_taken]
        if kind == faults.DEVICE_OOM:
            rec = self._budget_recommendation()
            cur = self._explicit_window()
            if (
                "budget_shrink" not in taken
                and rec is not None
                # an explicit window already at/below the recommendation
                # means the failed attempt ran it — re-applying the same
                # value would be recover_retry with extra steps
                and (not cur or int(cur) > int(rec))
            ):
                return "budget_shrink"
            if "recover_retry" not in taken:
                return "recover_retry"
            if "shrink_union_window" not in taken:
                return "shrink_union_window"
            if "spill_retry" not in taken and self._spill_applicable():
                return "spill_retry"
            return None
        if kind == faults.HOST_OOM:
            return "recover_retry" if "recover_retry" not in taken else None
        if kind == faults.IO_TRANSIENT:
            retries, _ = io_retry_budget()
            if sum(1 for r in taken if r == "io_backoff_retry") < retries:
                return "io_backoff_retry"
            return None
        if kind == faults.COMMIT_CONFLICT:
            # an aborted optimistic commit published NOTHING, so re-running
            # the transaction against the fresh head is safe whenever the
            # caller vouched for idempotence (can_retry). Sits ahead of
            # hard failure: bounded re-runs with jittered backoff.
            from .lakehouse.table import resolve_conflict_retries

            taken_n = sum(1 for r in taken if r == "commit_rebase_retry")
            if taken_n < resolve_conflict_retries():
                return "commit_rebase_retry"
            return None
        return None

    def _spill_applicable(self) -> bool:
        """True when an unpredicted device OOM can still retry through the
        host spill pool: the last planned statement carries an out-of-core
        seam (budget_plan records `spillable` for every verdict), spill
        isn't disabled, and the failed attempt didn't already run forced
        out-of-core (re-forcing an identical mode would be recover_retry
        with extra steps)."""
        conf = getattr(self.session, "conf", None)
        if conf is None:
            return False
        mode = str(conf.get("engine.spill", "auto")).lower()
        if mode in ("off", "force"):
            return False
        rec = (
            self._plan_budget_override
            if self._plan_budget_override is not None
            else getattr(self.session, "last_plan_budget", None)
        )
        return bool(isinstance(rec, dict) and rec.get("spillable"))

    def _apply_rung(self, rung: str, kind: str, prior_same_rung: int):
        io_attempt = prior_same_rung  # backoff exponent for retry rungs
        session = self.session
        if rung in ("recover_retry", "shrink_union_window", "budget_shrink",
                    "spill_retry"):
            if hasattr(session, "recover_memory"):
                session.recover_memory(
                    "device memory exhausted"
                    if kind == faults.DEVICE_OOM
                    else "host memory exhausted"
                )
        if rung == "budget_shrink":
            # consume the static prediction: retry with the budgeter's
            # window instead of walking recover->halve blind. Only ever
            # shrinks — a recommendation larger than an explicitly set
            # window must not grow the degradation back out.
            rec = self._budget_recommendation()
            conf = getattr(session, "conf", None)
            if conf is not None and rec:
                cur = self._explicit_window()
                new = min(int(cur), int(rec)) if cur else int(rec)
                conf["engine.union_agg_window_rows"] = new
                return {"window_rows": new}
            return None
        if rung == "shrink_union_window":
            # degrade persistently: halve the window the failed attempt
            # actually ran — the explicit conf, else the annotated static
            # window (conf unset means the annotation was in effect), else
            # force a small one — so every later query in this stream's
            # session routes blocked-union plans through bounded windows
            conf = getattr(session, "conf", None)
            if conf is not None:
                cur = self._explicit_window()
                if not cur:
                    pred = self._budget_prediction()
                    cur = (pred or {}).get("window_rows")
                new = max(int(cur) // 2, 4096) if cur else _DEGRADED_WINDOW_ROWS
                conf["engine.union_agg_window_rows"] = new
                return {"window_rows": new}
        if rung == "spill_retry":
            # graceful degradation ahead of hard failure: the retry routes
            # every eligible join/sort/distinct through the host spill
            # pool (exec's `force` mode). Persistent, like the window
            # shrink — later statements of this degraded session stay
            # out-of-core rather than re-walking the ladder per query.
            conf = getattr(session, "conf", None)
            if conf is not None:
                rec = (
                    self._plan_budget_override
                    if self._plan_budget_override is not None
                    else getattr(session, "last_plan_budget", None)
                ) or {}
                parts = (
                    int(rec.get("spill_partitions") or 0)
                    or _SPILL_RETRY_PARTS
                )
                conf["engine.spill"] = "force"
                conf["engine.spill_partitions"] = parts
                return {"partitions": parts}
            return None
        if rung == "io_backoff_retry":
            _, base = io_retry_budget()
            delay = next(faults.backoff_delays(1, base * (2 ** io_attempt)), 0.0)
            if delay:
                time.sleep(delay)
            return {"delay_s": round(delay, 3)}
        if rung == "commit_rebase_retry":
            # jittered backoff before re-running the aborted transaction:
            # the in-table loop already rebases append/append, so a
            # conflict reaching the ladder means overwrite writes derived
            # from a stale snapshot — the re-run re-derives them from the
            # fresh head (lakehouse/dml.py re-resolves its snapshot)
            from .lakehouse.table import commit_backoff_base

            prior = io_attempt  # caller passes prior same-rung count
            delay = next(
                faults.backoff_delays(
                    1, commit_backoff_base() * (2 ** prior)
                ),
                0.0,
            )
            if delay:
                time.sleep(delay)
            return {"delay_s": round(delay, 3)}
        return None

    def report_on(self, fn: Callable, *args, retry_oom: bool = False,
                  name: str = None, request_id: str = None,
                  plan_budget: dict = None):
        """Run fn(*args), recording env (secrets redacted), status and time.

        retry_oom: allow the retrying ladder rungs (caller must guarantee
        fn is idempotent — read-only queries yes, DML no). Non-idempotent
        callables still get classification, the watchdog, and full attempt
        records; they just never re-run.

        name: query/function label for emitted trace events (the summary
        itself gets its name later, in write_summary).

        request_id: serve-mode per-request id — threaded into the sink's
        in-flight record and every emitted event, so two tenants running
        the SAME query name concurrently on one session cannot clobber
        each other's /statusz state (each request retires only its own
        record).

        plan_budget: the budgeter record captured when THIS statement was
        planned (Session.plan_sql) — the ladder consumes it instead of
        the shared `session.last_plan_budget` field, which a concurrent
        request may have overwritten by retry time."""
        self._name = name
        self._request_id = request_id
        self._plan_budget_override = plan_budget
        env_vars = {
            k: v
            for k, v in os.environ.items()
            if not any(tag in k.upper() for tag in _REDACTED)
        }
        self.summary["env"]["envVars"] = env_vars
        conf = engine_conf(self.session)
        version = f"nds-tpu {__version__}"
        self.summary["env"]["sparkConf"] = conf
        self.summary["env"]["sparkVersion"] = version
        self.summary["env"]["engineConf"] = conf
        self.summary["env"]["engineVersion"] = version
        failures: list[str] = []
        registered = False
        try:
            self.session.register_listener(failures.append)
            registered = True
        except AttributeError:
            pass
        timeout = query_timeout(self.session)
        start_time = int(time.time() * 1000)
        start_mono = time.perf_counter()
        rungs: list[dict] = []
        attempt_errors: list[str] = []
        # memory high-water sampling rides with tracing OR with a
        # configured host-RSS watermark (pre-emption needs the samples
        # even when nothing is traced). Since the flight recorder, the
        # default tracer is ring-only rather than None, so the sampler
        # (and its heartbeat beacon — hang evidence for failure bundles)
        # runs for every reported query unless NDS_FLIGHT_RECORDER=off.
        watermark = host_rss_watermark(self.session)
        if hasattr(self.session, "_mem_pressure"):
            self.session._mem_pressure = False
        # hysteresis: RSS rarely drops back once crossed (allocators hold
        # onto pages), so without this every later query's fresh sampler
        # would re-fire on its first sample and re-halve the window down
        # to the floor. One shrink per excursion: the latch only re-arms
        # after a query starts BELOW the watermark again.
        if watermark and getattr(self.session, "_rss_above_watermark", False):
            from .obs.memwatch import rss_bytes

            r = rss_bytes()
            if r is not None and r < watermark:
                self.session._rss_above_watermark = False

        def _on_watermark(rss):
            # sampler-thread callback, fired at most once per query: shrink
            # the blocked-union window for the remaining windows (the
            # executor's window loop polls _mem_pressure) and for every
            # later statement of this session, and leave ladder evidence
            session = self.session
            if getattr(session, "_rss_above_watermark", False):
                return  # same excursion as a previous query: already shrunk
            session._rss_above_watermark = True
            conf = getattr(session, "conf", None)
            new = None
            if conf is not None:
                cur = self._explicit_window()
                new = max(int(cur) // 2, 4096) if cur else _DEGRADED_WINDOW_ROWS
                # never-grow invariant: an unset conf knob must not eclipse
                # a smaller static budget_window_rows window (conf wins
                # over the annotation in union_agg_window_rows), whether or
                # not that window was already annotated into the plan
                pred = self._budget_prediction()
                rec = (pred or {}).get("window_rows")
                if rec:
                    new = min(new, int(rec))
                conf["engine.union_agg_window_rows"] = new
            if hasattr(session, "_mem_pressure"):
                session._mem_pressure = True
            # host-tier relief: tier the spill pool's RAM-resident segments
            # down to disk BEFORE the allocator fails (the pool is touched
            # only if it already exists — pre-emption must not build one)
            spilled = 0
            pool = getattr(session, "_spill_pool", None)
            if pool is not None:
                try:
                    spilled = pool.evict_host()
                except Exception:
                    spilled = 0  # relief is best-effort, never fatal here
            rungs.append({
                "rung": "host_watermark_shrink",
                "kind": faults.HOST_OOM,
                "rss_bytes": int(rss),
                **({"window_rows": new} if new else {}),
                **({"spill_segments_evicted": spilled} if spilled else {}),
            })
            if self.tracer is not None:
                self.tracer.emit(
                    "mem_watermark", query=self._name, rss_bytes=int(rss),
                    watermark_bytes=watermark,
                    **({"window_rows": new} if new else {}),
                    **self._rid_fields(),
                )
            notify = getattr(session, "notify_failure", None)
            if notify is not None:
                notify(
                    f"host RSS watermark crossed ({rss} >= {watermark}); "
                    f"blocked-union window shrunk pre-emptively"
                )

        def _renew_lake_leases():
            # heartbeat-cadence lease renewal: a statement outliving
            # engine.lake_lease_ttl_s (a slow SF100-scale scan) must not
            # have its pinned snapshot vacuumed mid-read — before this,
            # leases only renewed on re-resolution
            cat = getattr(self.session, "catalog", None)
            if cat is not None and hasattr(cat, "renew_lake_leases"):
                cat.renew_lake_leases()

        # arm renewal only when the session actually serves lakehouse
        # tables: a parquet/arrow-only session must keep the historical
        # sampler-off fast path (no thread per statement)
        _cat = getattr(self.session, "catalog", None)
        renews_leases = (
            hasattr(_cat, "renew_lake_leases")
            and any(
                getattr(e, "fmt", None) == "lakehouse"
                for e in getattr(_cat, "entries", {}).values()
            )
        )
        sampler = (
            MemorySampler(
                watermark_bytes=watermark or None,
                on_watermark=_on_watermark if watermark else None,
                # the sampler thread doubles as the liveness beacon: it
                # heartbeats through the tracer (passed explicitly —
                # thread-locals don't reach the sampler thread) so a hung
                # attempt stays visible on /statusz and in the log tail
                tracer=self.tracer,
                query=name,
                on_heartbeat=_renew_lake_leases if renews_leases else None,
            )
            if self.tracer is not None or watermark or renews_leases
            else None
        )
        if self.sink is not None:
            # the app id keys the sink's in-flight record to THIS stream's
            # events (concurrent streams may run the same query name)
            self.sink.query_started(
                name, app=getattr(self.tracer, "app_id", None),
                request_id=request_id,
            )
        try:
            if sampler is not None:
                sampler.__enter__()
            att_t0 = time.perf_counter()
            err = self._attempt(fn, args, timeout)
            att_ms = (time.perf_counter() - att_t0) * 1000.0
            while err is not None:
                attempt_errors.append(err)
                kind = faults.classify(err)
                rung = self._next_rung(kind, rungs, can_retry=retry_oom)
                if rung is None:
                    break
                # backoff rungs escalate on their OWN prior count (io and
                # commit-conflict retries each walk their own exponent)
                same_rung_so_far = sum(
                    1 for r in rungs if r["rung"] == rung
                )
                detail = self._apply_rung(rung, kind, same_rung_so_far)
                entry = {"rung": rung, "kind": kind}
                if detail:
                    entry.update(detail)
                rungs.append(entry)
                if self.tracer is not None:
                    # attempt_ms: the FAILED attempt's wall this rung is
                    # recovering from — the critical-path profiler's
                    # ladder-retry cause reads exactly this
                    self.tracer.emit(
                        "ladder_rung", query=name, rung=rung,
                        failure_kind=kind, attempt_ms=round(att_ms, 3),
                        **(detail or {}),
                        **self._rid_fields(),
                    )
                att_t0 = time.perf_counter()
                err = self._attempt(fn, args, timeout)
                att_ms = (time.perf_counter() - att_t0) * 1000.0
            if err is not None and faults.classify(err) == faults.DEVICE_OOM:
                # terminal OOM: drop caches once more so the failure cannot
                # poison the remaining stream (reference analogue: executor
                # replaced after repeated task failure)
                if hasattr(self.session, "recover_memory"):
                    self.session.recover_memory("device memory exhausted")
        finally:
            if sampler is not None:
                sampler.__exit__(None, None, None)
            if registered:
                self.session.unregister_listener(failures.append)
        end_time = int(time.time() * 1000)
        # watermark pre-emption leaves ladder evidence but is not a retry
        self.summary["retries"] = sum(
            1 for r in rungs if r["rung"] != "host_watermark_shrink"
        )
        if rungs:
            self.summary["ladder"] = rungs
        if err is None:
            if attempt_errors:
                # recovered by the ladder: record what it took
                self.summary["exceptions"].extend(attempt_errors)
            if failures or attempt_errors:
                self.summary["queryStatus"].append("CompletedWithTaskFailures")
            else:
                self.summary["queryStatus"].append("Completed")
        else:  # a failed query must not abort the stream
            print(err)
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].extend(attempt_errors)
            self.summary["failureKind"] = faults.classify(err)
            # flight recorder: a terminal failure leaves a self-contained
            # bundle (ring + plan/budget/ladder/memory/conf) even with no
            # trace dir configured — reason names what exhausted
            kind = self.summary["failureKind"]
            self._flight_flush(
                "watchdog" if kind == faults.TIMEOUT
                else "ladder_exhausted" if rungs
                else "query_failed",
                rungs, sampler=sampler,
            )
        self.summary["startTime"] = start_time
        # epoch-ms difference is the queryTimes REPORT CONTRACT (reference
        # parity); the monotonic duration rides the query_span event below
        # nds-lint: disable=perf-counter
        self.summary["queryTimes"].append(end_time - start_time)
        if failures:
            self.summary["taskFailures"] = list(failures)
        if sampler is not None and sampler.peak_bytes is not None:
            self.summary["memoryHighWater"] = {
                "bytes": sampler.peak_bytes,
                "source": sampler.source,
            }
        if self.tracer is not None:
            ev = {
                "query": name,
                # monotonic duration: the epoch-ms queryTimes contract
                # stays, but the span (which operator spans are checked
                # against) must not jump with wall-clock adjustments
                "dur_ms": round((time.perf_counter() - start_mono) * 1000, 3),
                "status": self.summary["queryStatus"][-1],
                "retries": self.summary["retries"],
            }
            if err is not None:
                ev["failure_kind"] = self.summary["failureKind"]
            if sampler is not None and sampler.peak_bytes is not None:
                ev["mem_hw_bytes"] = sampler.peak_bytes
                ev["mem_source"] = sampler.source
                if sampler.peak_per_device is not None:
                    # per-device high-water (device-source runs): feeds
                    # the /statusz mesh section and failure bundles
                    ev["mem_hw_per_device"] = list(sampler.peak_per_device)
            ev.update(self._rid_fields())
            self.tracer.emit("query_span", **ev)
        return self.summary

    def write_summary(self, query_name: str, prefix: str = "") -> str:
        """Write `<prefix>-<query>-<startTime>.json` (reference keeps this
        exact name format for its Power-BI pipeline; we keep it for parity).
        The write is atomic (temp name + rename) so a crash mid-dump can't
        leave a torn JSON that later report parsing chokes on."""
        self.summary["query"] = query_name
        filename = f"{prefix}-{query_name}-{self.summary['startTime']}.json"
        self.summary["filename"] = filename
        with fs_open_atomic(filename, "w") as f:
            json.dump(self.summary, f, indent=2)
        return filename
