"""Differential validator: row-by-row comparison of two Power Run outputs.

TPU-native counterpart of the reference validator (reference:
nds/nds_validate.py — compare_results :47-111, collect_results :113-141,
rowEqual :143-164, compare :166-187, iterate_queries :189-227,
update_summary :229-263). Keeps the reference's exact semantics:

  * float/decimal compare with relative epsilon; NaN == NaN;
  * optional order-insensitive compare sorting on non-float columns first;
  * query78's rounded 4th column compared with absolute tolerance 0.01;
  * query65 always skipped, query67 skipped under float mode;
  * queryValidationStatus in {Pass, Fail, NotAttempted} written back into
    the per-query JSON summaries.

The reference compares CPU-Spark vs GPU-Spark runs of the same frontend;
here the same differential applies between any two engine runs (e.g. the
TPU mesh backend vs the single-device CPU backend, or vs the sqlite oracle
in tests/test_oracle.py).
"""

from __future__ import annotations

import glob
import json
import math
import os
from decimal import Decimal

import pyarrow.dataset as pads

from .io.fs import fs_open_atomic


def load_output(path: str, fmt: str):
    """Load one query's written output (power --output_prefix layout)."""
    return pads.dataset(path, format=fmt).to_table()


def collect_results(table, ignore_ordering: bool, batch_rows: int = 8192):
    """Rows as python lists, optionally sorted on non-float columns first
    (reference: collect_results :113-141, which streams via
    toLocalIterator). Python row objects are materialized one record batch
    at a time, so memory stays bounded at SF>=100 validation scale — the
    sort (when requested) happens in compact Arrow columnar form, never as
    Python lists."""

    import pyarrow.types as pat

    if ignore_ordering:
        non_float = [
            f.name for f in table.schema if not pat.is_floating(f.type)
        ]
        floats = [f.name for f in table.schema if pat.is_floating(f.type)]
        table = table.sort_by([(c, "ascending") for c in non_float + floats])

    def gen():
        for batch in table.to_batches(max_chunksize=batch_rows):
            cols = [
                batch.column(i).to_pylist()
                for i in range(batch.num_columns)
            ]
            if not cols:
                continue
            for row in zip(*cols):
                yield list(row)

    return gen()


def compare(expected, actual, epsilon=0.00001) -> bool:
    if isinstance(expected, float) and isinstance(actual, float):
        if math.isnan(expected) and math.isnan(actual):
            return True
        return math.isclose(expected, actual, rel_tol=epsilon)
    if isinstance(expected, str) and isinstance(actual, str):
        return expected == actual
    if expected is None and actual is None:
        return True
    if expected is None or actual is None:
        return False
    if isinstance(expected, Decimal) and isinstance(actual, Decimal):
        return math.isclose(expected, actual, rel_tol=epsilon)
    if isinstance(expected, (int, float, Decimal)) and isinstance(
        actual, (int, float, Decimal)
    ):
        # cross-type numeric (e.g. decimal vs float between engines)
        return math.isclose(float(expected), float(actual), rel_tol=epsilon)
    return expected == actual


def row_equal(row1, row2, epsilon, is_q78) -> bool:
    if is_q78:
        # q78's 4th column is round(ss_qty/(ws_qty+cs_qty), 2): allow 0.01
        # absolute difference (reference: rowEqual :143-162)
        row1, row2 = list(row1), list(row2)
        v1 = row1.pop(3)
        v2 = row2.pop(3)
        if v1 is None and v2 is None:
            fourth_eq = True
        elif v1 is None or v2 is None:
            fourth_eq = False
        else:
            fourth_eq = abs(float(v1) - float(v2)) <= 0.01
        return fourth_eq and all(
            compare(a, b, epsilon) for a, b in zip(row1, row2)
        )
    return all(compare(a, b, epsilon) for a, b in zip(row1, row2))


def compare_results(
    input1: str,
    input2: str,
    input1_format: str = "parquet",
    input2_format: str = "parquet",
    ignore_ordering: bool = False,
    is_q78: bool = False,
    max_errors: int = 10,
    epsilon: float = 0.00001,
) -> bool:
    """Row-by-row comparison of two query output dirs."""
    t1 = load_output(input1, input1_format)
    t2 = load_output(input2, input2_format)
    if t1.num_rows != t2.num_rows:
        print(f"DataFrame row counts do not match: {t1.num_rows} != {t2.num_rows}")
        return False
    r1 = collect_results(t1, ignore_ordering)
    r2 = collect_results(t2, ignore_ordering)
    errors = 0
    i = 0
    while i < t1.num_rows and errors < max_errors:
        lhs = next(r1)
        rhs = next(r2)
        if not row_equal(lhs, rhs, epsilon, is_q78):
            print(f"Row {i}: \n{lhs}\n{rhs}\n")
            errors += 1
        i += 1
    print(f"Processed {i} rows")
    if errors == max_errors:
        print(f"Aborting comparison after reaching maximum of {max_errors} errors")
        return False
    if errors == 0:
        print("Results match")
        return True
    print(f"There were {errors} errors")
    return False


def iterate_queries(
    input1: str,
    input2: str,
    queries: list,
    input1_format: str = "parquet",
    input2_format: str = "parquet",
    ignore_ordering: bool = False,
    max_errors: int = 10,
    epsilon: float = 0.00001,
    is_float: bool = False,
) -> list:
    """Compare every query's output dir; returns the unmatched query names."""
    unmatch_queries = []
    for query in queries:
        if query == "query65":
            # ambiguous ordering inside q65 (reference carve-out)
            continue
        if query == "query67" and is_float:
            continue
        print(f"=== Comparing Query: {query} ===")
        ok = compare_results(
            os.path.join(input1, query),
            os.path.join(input2, query),
            input1_format,
            input2_format,
            ignore_ordering,
            is_q78=query == "query78",
            max_errors=max_errors,
            epsilon=epsilon,
        )
        if not ok:
            unmatch_queries.append(query)
    if unmatch_queries:
        print(f"=== Unmatch Queries: {unmatch_queries} ===")
    return unmatch_queries


def update_summary(prefix: str, unmatch_queries: list, query_names: list):
    """Write queryValidationStatus into each query's JSON summary
    (reference: update_summary :229-263)."""
    if not os.path.exists(prefix):
        raise Exception("The json summary folder doesn't exist.")
    print(f"Updating queryValidationStatus in folder {prefix}.")
    for query_name in query_names:
        file_glob = glob.glob(os.path.join(prefix, f"*{query_name}-*.json"))
        if len(file_glob) > 1:
            raise Exception(
                f"More than one summary file found for query {query_name} in folder {prefix}."
            )
        if not file_glob:
            raise Exception(
                f"No summary file found for query {query_name} in folder {prefix}."
            )
        filename = file_glob[0]
        with open(filename) as f:
            summary = json.load(f)
        if query_name in unmatch_queries:
            if (
                "Completed" in summary["queryStatus"]
                or "CompletedWithTaskFailures" in summary["queryStatus"]
            ):
                summary["queryValidationStatus"] = ["Fail"]
            else:
                summary["queryValidationStatus"] = ["NotAttempted"]
        else:
            summary["queryValidationStatus"] = ["Pass"]
        # atomic rewrite: this is the query's ONLY summary JSON — a crash
        # mid-dump must leave the previous complete file, not a torn one
        with fs_open_atomic(filename, "w") as f:
            json.dump(summary, f, indent=2)
