"""Snapshot-manifest table layer: the Iceberg/Delta-equivalent ACID surface.

The reference runs Data Maintenance against Iceberg or Delta Lake warehouses
(reference: nds/nds_maintenance.py:118-202, nds/nds_rollback.py:46-51). The
TPU framework needs the same capabilities — atomic INSERT/DELETE, snapshot
history, timestamp rollback — without a JVM catalog service. This layer
provides them with immutable parquet data files plus a JSON manifest log:

    <table>/
      data/part-<version>-<n>.parquet      (immutable)
      _manifests/v000001.json ...          (one per snapshot)

A snapshot lists the data files that constitute the table at that version.
Writers stage data files first, then commit by writing the next manifest
(atomic via os.rename), so readers always see a consistent snapshot.
Rollback appends a new manifest replaying an older file list — history is
never rewritten, matching Iceberg's rollback_to_timestamp semantics.
"""

from __future__ import annotations

import json
import os
import time
import uuid

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

_MANIFEST_DIR = "_manifests"
_DATA_DIR = "data"


class LakehouseError(Exception):
    pass


class LakehouseTable:
    def __init__(self, path: str):
        self.path = path
        self.manifest_dir = os.path.join(path, _MANIFEST_DIR)
        self.data_dir = os.path.join(path, _DATA_DIR)
        if not os.path.isdir(self.manifest_dir):
            raise LakehouseError(f"{path} is not a lakehouse table")

    # -- creation ----------------------------------------------------------
    @classmethod
    def create(cls, path: str, batches=None, schema: pa.Schema | None = None):
        """Create an empty table (or one seeded from an iterable of record
        batches / a pa.Table)."""
        os.makedirs(os.path.join(path, _MANIFEST_DIR), exist_ok=True)
        os.makedirs(os.path.join(path, _DATA_DIR), exist_ok=True)
        t = cls(path)
        staged = t._stage(batches, schema) if batches is not None else []
        if schema is None and staged:
            schema = pq.read_schema(os.path.join(path, staged[0][0]))
        t._commit(staged, "create", base_files=[], schema=schema)
        return t

    @classmethod
    def is_table(cls, path: str) -> bool:
        return os.path.isdir(os.path.join(path, _MANIFEST_DIR))

    # -- snapshot log ------------------------------------------------------
    def versions(self):
        """[(version, timestamp_ms, operation)] ascending."""
        out = []
        for f in sorted(os.listdir(self.manifest_dir)):
            if f.startswith("v") and f.endswith(".json"):
                with open(os.path.join(self.manifest_dir, f)) as fh:
                    m = json.load(fh)
                out.append((m["version"], m["timestamp_ms"], m["operation"]))
        return out

    def _manifest(self, version: int) -> dict:
        p = os.path.join(self.manifest_dir, f"v{version:06d}.json")
        with open(p) as fh:
            return json.load(fh)

    def current_version(self) -> int:
        vs = [v for v, _, _ in self.versions()]
        if not vs:
            raise LakehouseError(f"{self.path}: no snapshots")
        return max(vs)

    def current_files(self):
        m = self._manifest(self.current_version())
        return [os.path.join(self.path, f) for f in m["files"]]

    def num_rows(self) -> int:
        m = self._manifest(self.current_version())
        return m.get("num_rows", -1)

    # -- reads -------------------------------------------------------------
    def dataset(self) -> pads.Dataset:
        files = self.current_files()
        if not files:
            # empty snapshot: in-memory empty dataset over the stored schema
            schema = self.schema()
            if schema is None:
                raise LakehouseError(f"{self.path}: empty table with no schema")
            return pads.dataset(schema.empty_table())
        return pads.dataset(files, format="parquet")

    def schema(self) -> pa.Schema | None:
        files = self.current_files()
        if files:
            return pq.read_schema(files[0])
        m = self._manifest(self.current_version())
        if m.get("schema_hex"):
            # an all-rows DELETE leaves zero data files; the manifest still
            # carries the schema so the table stays readable
            import pyarrow.ipc as ipc

            return ipc.read_schema(
                pa.BufferReader(bytes.fromhex(m["schema_hex"]))
            )
        return None

    # -- writes ------------------------------------------------------------
    def _stage(self, batches, schema=None):
        """Write data files; returns [(relpath, num_rows)]. Not yet visible."""
        if isinstance(batches, pa.Table):
            batches = batches.to_batches(max_chunksize=1 << 20)
        staged = []
        writer = None
        relpath = None
        n_rows = 0
        try:
            for b in batches:
                if writer is None:
                    relpath = os.path.join(
                        _DATA_DIR, f"part-{uuid.uuid4().hex[:12]}.parquet"
                    )
                    writer = pq.ParquetWriter(
                        os.path.join(self.path, relpath),
                        schema or b.schema,
                        compression="snappy",
                    )
                writer.write_batch(b)
                n_rows += b.num_rows
        finally:
            if writer is not None:
                writer.close()
        if relpath is not None:
            staged.append((relpath, n_rows))
        return staged

    def _commit(self, staged, operation, base_files=None, num_rows=None, schema=None):
        """Append the next manifest: base file list + staged files."""
        schema_hex = None
        if schema is not None:
            schema_hex = bytes(schema.serialize()).hex()
        try:
            cur = self._manifest(self.current_version())
            version = cur["version"] + 1
            base = cur["files"] if base_files is None else base_files
            base_rows = cur.get("num_rows", 0) if base_files is None else 0
            prev_ts = cur["timestamp_ms"]
            if schema_hex is None:
                schema_hex = cur.get("schema_hex")
        except LakehouseError:
            version, base, base_rows, prev_ts = 1, base_files or [], 0, 0
        files = list(base) + [p for p, _ in staged]
        total = (
            num_rows
            if num_rows is not None
            else base_rows + sum(n for _, n in staged)
        )
        manifest = {
            "version": version,
            # strictly monotonic so timestamp rollback can never tie between
            # adjacent snapshots (Iceberg has the same guarantee)
            "timestamp_ms": max(int(time.time() * 1000), prev_ts + 1),
            "operation": operation,
            "files": files,
            "num_rows": total,
            "schema_hex": schema_hex,
        }
        tmp = os.path.join(self.manifest_dir, f".tmp-{uuid.uuid4().hex}.json")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        # optimistic concurrency: os.link refuses to clobber an existing
        # manifest, so a concurrent writer that claimed the same version
        # fails loudly instead of silently last-writer-winning (Iceberg's
        # commit-conflict guarantee)
        dest = os.path.join(self.manifest_dir, f"v{version:06d}.json")
        try:
            os.link(tmp, dest)
        except FileExistsError:
            os.unlink(tmp)
            raise LakehouseError(
                f"{self.path}: concurrent commit conflict at version "
                f"{version}; retry the transaction"
            )
        os.unlink(tmp)
        return version

    def append(self, table, operation="append") -> int:
        """INSERT: add rows (pa.Table or batch iterable) as new immutable
        files; returns the new version."""
        staged = self._stage(table)
        return self._commit(staged, operation)

    def replace(self, table: pa.Table, operation="overwrite") -> int:
        """Replace the full file set (copy-on-write DELETE/UPDATE)."""
        staged = self._stage(table)
        return self._commit(
            staged, operation, base_files=[],
            num_rows=sum(n for _, n in staged),
        )

    # -- time travel -------------------------------------------------------
    def rollback_to_version(self, version: int) -> int:
        m = self._manifest(version)
        return self._commit(
            [], f"rollback-to-v{version}", base_files=m["files"],
            num_rows=m.get("num_rows"),
        )

    def rollback_to_timestamp(self, ts_ms: int) -> int:
        """Roll back to the last snapshot at or before ts_ms (reference:
        CALL spark_catalog.system.rollback_to_timestamp, nds_rollback.py:46-51)."""
        candidates = [v for v, t, _ in self.versions() if t <= ts_ms]
        if not candidates:
            raise LakehouseError(
                f"{self.path}: no snapshot at or before {ts_ms}"
            )
        return self.rollback_to_version(max(candidates))
