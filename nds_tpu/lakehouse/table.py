"""Snapshot-manifest table layer: the Iceberg/Delta-equivalent ACID surface.

The reference runs Data Maintenance against Iceberg or Delta Lake warehouses
(reference: nds/nds_maintenance.py:118-202, nds/nds_rollback.py:46-51). The
TPU framework needs the same capabilities — atomic INSERT/DELETE, snapshot
history, timestamp rollback — without a JVM catalog service. This layer
provides them with immutable parquet data files plus a JSON manifest log:

    <table>/
      data/part-<pid>-<n>.parquet          (immutable)
      _manifests/v000001.json ...          (one per snapshot)

A snapshot lists the data files that constitute the table at that version.
Writers stage data files first, then commit by publishing the next manifest
(create-exclusive), so readers always see a consistent snapshot. Rollback
appends a new manifest replaying an older file list — history is never
rewritten, matching Iceberg's rollback_to_timestamp semantics.

Fleet concurrency: with `engine.lake_catalog` configured the publish,
reader-lease registration, and vacuum fence route through the catalog
service (lakehouse/catalog.py — fs CAS or a tcp coordinator), giving
multi-HOST writers commit arbitration, cross-host lease visibility, and
epoch fencing (a stale zombie writer can never publish). Off by default:
everything below then describes the process-concurrent behavior exactly.

Concurrency (the Iceberg optimistic-concurrency model, in-process scale):

* **Snapshot-isolated reads** — `snapshot(version)` returns a TableSnapshot
  read handle resolved ONCE; every read through it (dataset/schema/files)
  sees exactly that manifest, immune to racing commits. The engine pins one
  snapshot per query at plan time (engine/session.py) and registers the pin
  in the process-wide reader-lease table (lakehouse/leases.py) so vacuum
  can never delete a file under a live reader.
* **OCC commit with rebase** — `_commit` claims the next version with a
  create-exclusive publish. A loser whose transaction is append-only
  (base = current head) REBASES: it re-reads the new head and retries with
  the new base file list (bounded by `engine.lake_commit_retries`, jittered
  backoff), so append/append conflicts converge with both row sets present.
  A loser that replaces the file set (overwrite/delete/rollback/create)
  aborts with CommitConflictError — its writes were derived from a snapshot
  that is no longer the head — and the report ladder's `commit_rebase_retry`
  rung re-runs the whole transaction against the fresh snapshot.
* **Vacuum + crash hygiene** — `expire_snapshots` drops old manifests
  (never the head, never a leased version); `vacuum` deletes data files
  referenced by no retained manifest, no live reader lease, and no live
  writer's in-flight stage. Staged files and manifest temps embed the
  writer pid, so `sweep_orphans` (run once per process at session start)
  can remove a crashed writer's staged-but-uncommitted files and torn
  `.tmp-*` manifests without ever touching a live or foreign file — the
  same pid-manifest pattern as engine/spill.py's pool sweep.

Failure domain: `stage:<table>`, `manifest:<table>` and `vacuum:<table>`
are io/crash fault-injection sites, and `commit:<table>` fires before the
manifest publish (a crash there leaves staged orphans but a fully readable
previous snapshot — the all-or-nothing guarantee).

All IO routes through the fsspec seam (io/fs.py), so a table may live on a
local path, memory:// (tests), or any cloud URL — the reference reaches
HDFS/S3/GS in every phase and a multi-host run needs a shared warehouse.
"""

from __future__ import annotations

import json
import os
import posixpath
import re
import time
import uuid

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..io.fs import get_fs, put_if_absent
from .catalog import CatalogFencedError, resolve_catalog, resolve_writer_ttl
from .leases import LEASES
from .zonemap import StatsAccumulator

_MANIFEST_DIR = "_manifests"
_DATA_DIR = "data"

#: staged data files / manifest temps embed the writer pid so crash
#: hygiene can liveness-check the owner (spill.py's pid-manifest pattern);
#: with a catalog configured they ALSO embed the writer's fencing epoch
#: (`part-<pid>-e<epoch>-<hex>.parquet`) so vacuum can attribute stages
#: across hosts without pids. Pre-existing tables' `part-<hex>.parquet`
#: files still read fine through their manifests — the sweep just never
#: attributes (or touches) them
_STAGED_RE = re.compile(r"^part-(\d+)(?:-e(\d+))?-[0-9a-f]{12}\.parquet$")
_TMP_MANIFEST_RE = re.compile(r"^\.tmp-(\d+)-[0-9a-f]+\.json$")
_DATA_FILE_RE = re.compile(r"^part-[0-9a-f-]+\.parquet$")

#: bounded rebase budget for append/append commit conflicts
#: (conf `engine.lake_commit_retries` / env NDS_LAKE_COMMIT_RETRIES)
DEFAULT_COMMIT_RETRIES = 5

#: backoff base (seconds) between rebase attempts — full jitter via
#: faults.backoff_delays; 0 makes tests deterministic
COMMIT_BACKOFF_ENV = "NDS_LAKE_COMMIT_BACKOFF"

#: test seam for the interleaving harness: when set, called as
#: hook(table_basename, operation, version) right before every publish
#: attempt — deterministic schedule control over commit points (barriers
#: force two writers onto one version, or land a commit between a pinned
#: reader's two scans). None in production: one attribute check per commit.
_COMMIT_HOOK = None


class LakehouseError(Exception):
    pass


class CommitConflictError(LakehouseError):
    """An optimistic commit lost the publish race and could not (or must
    not) be rebased. Classified `commit_conflict` (faults._COMMIT_PAT):
    the transaction never published, so re-running it against the fresh
    head is safe — the report ladder's `commit_rebase_retry` rung does
    exactly that with jittered backoff."""


class _ChunkAlreadyIngested(LakehouseError):
    """Internal commit-point signal: every chunk id this transaction
    carries is already in the head's ingest ledger, so publishing would
    duplicate rows. Carries the head version; callers discard their
    staged files and treat the chunk as done (exactly-once)."""

    def __init__(self, version: int):
        super().__init__(f"chunk already ingested at v{version}")
        self.version = int(version)


def resolve_commit_retries(conf: dict | None = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.lake_commit_retries")
    if v is None:
        v = os.environ.get("NDS_LAKE_COMMIT_RETRIES")
    try:
        return max(int(v), 0) if v is not None and v != "" else (
            DEFAULT_COMMIT_RETRIES
        )
    except (TypeError, ValueError):
        return DEFAULT_COMMIT_RETRIES


def commit_backoff_base() -> float:
    """Jittered-backoff base seconds for commit-conflict retries — the ONE
    parse shared by the in-table rebase loop, the report ladder's
    `commit_rebase_retry` rung, and maintenance's statement-level re-run."""
    try:
        return max(float(os.environ.get(COMMIT_BACKOFF_ENV, "0.05")), 0.0)
    except ValueError:
        return 0.05


def resolve_compact_target_bytes(conf: dict | None = None) -> int:
    """Compaction size goal: files below this are rewrite candidates and
    groups are packed up to roughly this size (conf
    `engine.lake_compact_target_bytes` / env
    NDS_LAKE_COMPACT_TARGET_BYTES, default 128 MiB)."""
    v = None
    if conf:
        v = conf.get("engine.lake_compact_target_bytes")
    if v is None:
        v = os.environ.get("NDS_LAKE_COMPACT_TARGET_BYTES")
    try:
        return max(int(v), 1) if v not in (None, "") else 128 << 20
    except (TypeError, ValueError):
        return 128 << 20


def resolve_compact_min_files(conf: dict | None = None) -> int:
    """Minimum small-file count before a compaction rewrite is worth a
    commit (conf `engine.lake_compact_min_files` / env
    NDS_LAKE_COMPACT_MIN_FILES, default 4)."""
    v = None
    if conf:
        v = conf.get("engine.lake_compact_min_files")
    if v is None:
        v = os.environ.get("NDS_LAKE_COMPACT_MIN_FILES")
    try:
        return max(int(v), 2) if v not in (None, "") else 4
    except (TypeError, ValueError):
        return 4


def resolve_conflict_retries() -> int:
    """How many times an aborted overwrite TRANSACTION may re-run (env
    NDS_LAKE_CONFLICT_RETRIES, default 2) — shared by the report ladder
    and maintenance's statement-level retry (the rebase loop inside
    `_commit` has its own budget, resolve_commit_retries)."""
    try:
        return max(
            int(os.environ.get("NDS_LAKE_CONFLICT_RETRIES", "2")), 0
        )
    except ValueError:
        return 2


def _tracer():
    # lazy import: the table layer must stay importable without obs, and
    # the thread-local binding is how session-less layers find their
    # stream's tracer (same pattern as faults.FaultRegistry.fire)
    from ..obs import trace as _obs_trace

    return _obs_trace.current()


class TableSnapshot:
    """Immutable read handle pinned at one manifest version. Every read
    resolves against the captured manifest — never the (possibly moved)
    table head — which is what makes a query scanning a table twice
    mid-`replace()` see ONE consistent snapshot."""

    def __init__(self, table: "LakehouseTable", manifest: dict):
        self.table = table
        self.manifest = manifest
        self.version = int(manifest["version"])
        self.timestamp_ms = int(manifest["timestamp_ms"])
        self.operation = manifest.get("operation")

    @property
    def rel_files(self):
        """Manifest-relative data file paths (the lease currency)."""
        return list(self.manifest["files"])

    def files(self):
        return [
            posixpath.join(self.table.root, f) for f in self.manifest["files"]
        ]

    def num_rows(self) -> int:
        return self.manifest.get("num_rows", -1)

    def schema(self) -> pa.Schema | None:
        files = self.files()
        if files:
            with self.table.fs.open(files[0], "rb") as fh:
                return pq.read_schema(fh)
        if self.manifest.get("schema_hex"):
            # an all-rows DELETE leaves zero data files; the manifest still
            # carries the schema so the table stays readable
            import pyarrow.ipc as ipc

            return ipc.read_schema(
                pa.BufferReader(bytes.fromhex(self.manifest["schema_hex"]))
            )
        return None

    def file_stats(self) -> dict:
        """Per-file zone maps recorded at commit time:
        {relpath: {"rows": n, "columns": {col: {"min","max","nulls"}}}}.
        Empty for manifests written before the stats schema (back-compat:
        a file absent from stats is simply never pruned)."""
        return self.manifest.get("stats") or {}

    def ingest_chunks(self) -> set:
        """Chunk ids the ingest ledger records as committed — the
        exactly-once resume checkpoint (see LakehouseTable.ingest_chunk)."""
        return set(self.manifest.get("ingest_chunks") or [])

    def dataset(self, files=None) -> pads.Dataset:
        """Dataset over the snapshot's files — or, with `files` (an
        iterable of manifest-relative paths, e.g. a zone-map pruned
        subset), over exactly those files in manifest order."""
        if files is not None:
            subset = set(files)
            paths = [
                posixpath.join(self.table.root, f)
                for f in self.manifest["files"]
                if f in subset
            ]
        else:
            paths = self.files()
        if not paths:
            # empty snapshot: in-memory empty dataset over the stored schema
            schema = self.schema()
            if schema is None:
                raise LakehouseError(
                    f"{self.table.path}: empty table with no schema"
                )
            return pads.dataset(schema.empty_table())
        return pads.dataset(paths, format="parquet", filesystem=self.table.fs)


class LakehouseTable:
    def __init__(self, path: str, conf: dict | None = None):
        self.path = str(path)
        self.conf = conf  # optional engine conf tier (commit/vacuum knobs)
        self.fs, self.root = get_fs(path)
        self.manifest_dir = posixpath.join(self.root, _MANIFEST_DIR)
        self.data_dir = posixpath.join(self.root, _DATA_DIR)
        # fleet catalog (lakehouse/catalog.py): when configured, commits,
        # reader leases and the vacuum fence route through it — cross-host
        # arbitration. None (the default) keeps the PR-10 process-
        # concurrent behavior byte for byte.
        self.catalog = resolve_catalog(conf)
        self._writer_token = None  # lazy catalog writer registration
        if not self.fs.isdir(self.manifest_dir):
            raise LakehouseError(f"{path} is not a lakehouse table")

    @property
    def name(self) -> str:
        return posixpath.basename(self.root)

    def _is_local(self) -> bool:
        """True for local-POSIX tables, where a pid embedded in a staged
        file name can be liveness-checked. Remote/shared stores (s3, gs,
        memory, ...) get the conservative path: never attribute by pid."""
        proto = (
            self.fs.protocol
            if isinstance(self.fs.protocol, str)
            else self.fs.protocol[0]
        )
        return proto in ("file", "local")

    # -- fleet catalog -----------------------------------------------------
    def _writer_epoch(self) -> int | None:
        """This instance's catalog writer epoch (registering a TTL-bounded
        writer lease on first use); None with no catalog configured."""
        if self.catalog is None:
            return None
        if self._writer_token is None:
            self._writer_token = self.catalog.writer_register(
                self, resolve_writer_ttl(self.conf)
            )
        return int(self._writer_token["epoch"])

    def _release_writer(self):
        """Drop this instance's writer lease after its transaction ends
        (published or aborted-and-discarded): the fence can then advance
        past the epoch at the next vacuum instead of waiting out the TTL.
        The next transaction on this instance re-registers."""
        token, self._writer_token = self._writer_token, None
        if token is None or self.catalog is None:
            return
        try:
            # a writer lease is a writer-epoch record in the same store;
            # the catalog expires it immediately by zeroing its TTL
            self.catalog.writer_renew(self, token, 0.0)
        except Exception:
            pass  # TTL expiry is the backstop

    def acquire_reader_lease(self, snapshot, ttl_s: float) -> int:
        """Register a reader lease over a snapshot's files: in the
        process-wide lease table ALWAYS, and — with a catalog configured
        — written through to the catalog so vacuum on ANY host sees it
        (the in-process table is then the local cache of catalog state).
        Returns the local lease id (renew/release forward to the remote
        half automatically)."""
        remote = None
        if self.catalog is not None:
            remote = self.catalog.lease_acquire(
                self, snapshot.version, snapshot.rel_files, ttl_s
            )
        return LEASES.acquire(
            self.root, snapshot.version, snapshot.rel_files, ttl_s,
            remote=remote,
        )

    def _held_files(self) -> set:
        """Files protected by live reader leases: the local table merged
        with the catalog's cross-host view."""
        out = LEASES.held_files(self.root)
        if self.catalog is not None:
            out |= self.catalog.held_files(self)
        return out

    def _held_versions(self) -> set:
        out = LEASES.held_versions(self.root)
        if self.catalog is not None:
            out |= self.catalog.held_versions(self)
        return out

    # -- creation ----------------------------------------------------------
    @classmethod
    def create(cls, path: str, batches=None, schema: pa.Schema | None = None):
        """Create an empty table (or one seeded from an iterable of record
        batches / a pa.Table)."""
        fs, root = get_fs(path)
        fs.makedirs(posixpath.join(root, _MANIFEST_DIR), exist_ok=True)
        fs.makedirs(posixpath.join(root, _DATA_DIR), exist_ok=True)
        t = cls(path)
        staged = t._stage(batches, schema) if batches is not None else []
        if schema is None and staged:
            with t.fs.open(posixpath.join(t.root, staged[0][0]), "rb") as fh:
                schema = pq.read_schema(fh)
        try:
            t._commit(staged, "create", base_files=[], schema=schema)
        except CommitConflictError:
            t._discard_staged(staged)
            raise
        return t

    @classmethod
    def is_table(cls, path: str) -> bool:
        fs, root = get_fs(path)
        return fs.isdir(posixpath.join(root, _MANIFEST_DIR))

    # -- snapshot log ------------------------------------------------------
    def _version_numbers(self):
        """Snapshot version numbers ascending, from manifest FILENAMES
        alone (v%06d.json encodes the version) — no manifest is opened,
        so head resolution stays O(1 listing) however long the history
        grows (per-statement pins would otherwise read every manifest)."""
        out = []
        for f in self.fs.ls(self.manifest_dir, detail=False):
            name = posixpath.basename(f)
            if name.startswith("v") and name.endswith(".json"):
                try:
                    out.append(int(name[1:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def versions(self):
        """[(version, timestamp_ms, operation)] ascending. Tolerates a
        manifest vanishing between the listing and the read: a concurrent
        `expire_snapshots` (the maintenance-under-load phase runs vacuum
        WHILE streams re-resolve heads) deletes old manifests, and a
        reader racing it must see the post-expiry log, not crash."""
        out = []
        for f in sorted(self.fs.ls(self.manifest_dir, detail=False)):
            name = posixpath.basename(f)
            if name.startswith("v") and name.endswith(".json"):
                try:
                    with self.fs.open(f, "r") as fh:
                        m = json.load(fh)
                except FileNotFoundError:
                    continue  # expired under us: same as never listed
                out.append((m["version"], m["timestamp_ms"], m["operation"]))
        return out

    def _manifest(self, version: int) -> dict:
        from .. import faults

        if faults.active():
            # io/crash injection site for manifest reads: a flaky store
            # failing a head re-read mid-rebase must walk the io ladder
            faults.maybe_fire(f"manifest:{self.name}", kinds=("io", "crash"))
        p = posixpath.join(self.manifest_dir, f"v{version:06d}.json")
        try:
            with self.fs.open(p, "r") as fh:
                return json.load(fh)
        except FileNotFoundError:
            raise LakehouseError(f"{self.path}: no snapshot v{version}")

    def current_version(self) -> int:
        vs = self._version_numbers()
        if not vs:
            raise LakehouseError(f"{self.path}: no snapshots")
        return max(vs)

    def snapshot(self, version: int | None = None) -> TableSnapshot:
        """Pinned read handle: resolve (current or explicit) version ONCE;
        all reads through the handle see exactly that manifest."""
        if version is None:
            version = self.current_version()
        return TableSnapshot(self, self._manifest(version))

    def current_files(self):
        return self.snapshot().files()

    def num_rows(self) -> int:
        return self.snapshot().num_rows()

    # -- reads -------------------------------------------------------------
    def dataset(self) -> pads.Dataset:
        return self.snapshot().dataset()

    def schema(self) -> pa.Schema | None:
        return self.snapshot().schema()

    # -- writes ------------------------------------------------------------
    def _stage(self, batches, schema=None):
        """Write data files; returns [(relpath, num_rows, stats)] where
        stats is the file's zone map ({"rows", "columns": {...min/max/
        nulls...}}), computed from the same batch stream that built the
        file — no second read. Not yet visible. File names embed this
        process's pid (crash-hygiene attribution)."""
        from .. import faults

        if faults.active():
            # io/crash injection site for staged-data writes: a crash here
            # leaves orphaned data files and NO manifest — the sweep's food
            faults.maybe_fire(f"stage:{self.name}", kinds=("io", "crash"))
        if isinstance(batches, pa.Table):
            batches = batches.to_batches(max_chunksize=1 << 20)
        staged = []
        writer = None
        out = None
        relpath = None
        acc = StatsAccumulator()
        # with a catalog, staged names carry the writer's fencing epoch so
        # a vacuum on ANY host can attribute the stage (pids are host-local)
        epoch_tag = (
            f"-e{self._writer_epoch()}" if self.catalog is not None else ""
        )
        try:
            for b in batches:
                if writer is None:
                    relpath = posixpath.join(
                        _DATA_DIR,
                        f"part-{os.getpid()}{epoch_tag}"
                        f"-{uuid.uuid4().hex[:12]}.parquet",
                    )
                    out = self.fs.open(
                        posixpath.join(self.root, relpath), "wb"
                    )
                    writer = pq.ParquetWriter(
                        out, schema or b.schema, compression="snappy"
                    )
                writer.write_batch(b)
                acc.update(b)
        finally:
            if writer is not None:
                writer.close()
            if out is not None:
                out.close()
        if relpath is not None:
            staged.append((relpath, acc.rows, acc.finish()))
        return staged

    def stage_clustered(self, tbl: pa.Table, cluster_by=None,
                        max_file_bytes=None):
        """Stage a table as one or more files CLUSTERED on `cluster_by`:
        rows are sorted by the key and split into ~`max_file_bytes`
        slices, so each staged file covers a narrow, mostly-disjoint key
        range and its zone map actually prunes (an unsorted split gives
        every file the full key range — zone maps that never exclude
        anything). Returns the combined staged list for one `_commit`."""
        if max_file_bytes is None:
            max_file_bytes = resolve_compact_target_bytes(self.conf)
        if tbl.num_rows == 0:
            return []
        if cluster_by and cluster_by in tbl.schema.names:
            import pyarrow.compute as pc

            tbl = tbl.take(
                pc.sort_indices(tbl, sort_keys=[(cluster_by, "ascending")])
            )
        n_files = max(1, -(-tbl.nbytes // max(int(max_file_bytes), 1)))
        per = -(-tbl.num_rows // n_files)
        staged = []
        for off in range(0, tbl.num_rows, per):
            staged.extend(self._stage(tbl.slice(off, per)))
        return staged

    def _discard_staged(self, staged):
        """Best-effort cleanup of staged files after an aborted commit (the
        orphan sweep is the backstop for anything missed)."""
        for s in staged:
            try:
                self.fs.rm_file(posixpath.join(self.root, s[0]))
            except OSError:
                pass
        self._release_writer()  # the aborted transaction's epoch is done

    def _commit(self, staged, operation, base_files=None, num_rows=None,
                schema=None, base_stats=None, base_chunks=None,
                new_chunks=None):
        """Publish the next manifest: base file list + staged files.

        Optimistic concurrency with bounded rebase: each attempt reads the
        head, claims head+1 with a create-exclusive publish, and on losing
        the race either REBASES (base_files is None — the transaction is
        append-only, so replaying it onto the new head's file list is
        exactly Iceberg's fast-append retry) or ABORTS with
        CommitConflictError (an explicit base file list means the writes
        were derived from a snapshot that is no longer the head; publishing
        would silently drop the winner's rows).

        Zone maps ride along: staged entries carry their file's stats,
        base files inherit the stats of whichever manifest supplied the
        base list (the rebased-onto head for appends, `base_stats` for
        explicit-base transactions), so the `stats` key stays exactly in
        sync with `files` through every rebase. Same story for the ingest
        ledger (`ingest_chunks` + `new_chunks`): appends union the head's
        ledger with this commit's chunk ids — and when every new chunk id
        is ALREADY in the head's ledger the publish is skipped with
        _ChunkAlreadyIngested, which is what makes chunk replay after a
        mid-commit kill exactly-once at the commit point, not merely
        at the (racy) pre-flight ledger check."""
        from .. import faults

        if faults.active():
            # failure-domain injection site: a fault here lands BEFORE the
            # manifest publish, so staged data files exist but no snapshot
            # references them — proving commits are all-or-nothing under
            # io/crash faults (Iceberg's commit-point guarantee)
            faults.maybe_fire(f"commit:{self.name}")
            faults.maybe_fire_path(self.root)
        schema_hex = None
        if schema is not None:
            schema_hex = bytes(schema.serialize()).hex()
        retries = resolve_commit_retries(self.conf)
        delays = faults.backoff_delays(retries, commit_backoff_base())
        attempts = 0
        while True:
            attempts += 1
            try:
                cur = self._manifest(self.current_version())
                version = cur["version"] + 1
                base = cur["files"] if base_files is None else base_files
                base_rows = (
                    cur.get("num_rows", 0) if base_files is None else 0
                )
                if base_files is None:
                    bstats = cur.get("stats") or {}
                    bchunks = set(cur.get("ingest_chunks") or [])
                else:
                    bstats = base_stats or {}
                    bchunks = set(base_chunks or [])
                prev_ts = cur["timestamp_ms"]
                if schema_hex is None:
                    schema_hex = cur.get("schema_hex")
            except LakehouseError:
                version, base, base_rows, prev_ts = 1, base_files or [], 0, 0
                bstats = base_stats or {}
                bchunks = set(base_chunks or [])
            if new_chunks and set(new_chunks) <= bchunks:
                # a concurrent (or previous, pre-kill) replay of the same
                # chunk already published: adding our staged copy would
                # double the rows
                raise _ChunkAlreadyIngested(cur["version"])
            files = list(base) + [s[0] for s in staged]
            total = (
                num_rows
                if num_rows is not None
                else base_rows + sum(s[1] for s in staged)
            )
            stats = {f: bstats[f] for f in base if f in bstats}
            for s in staged:
                if len(s) > 2 and s[2]:
                    stats[s[0]] = s[2]
            chunks = sorted(bchunks | set(new_chunks or []))
            manifest = {
                "version": version,
                # strictly monotonic so timestamp rollback can never tie
                # between adjacent snapshots (Iceberg's same guarantee)
                "timestamp_ms": max(int(time.time() * 1000), prev_ts + 1),
                "operation": operation,
                "files": files,
                "num_rows": total,
                "schema_hex": schema_hex,
            }
            if stats:
                manifest["stats"] = stats
            if chunks:
                manifest["ingest_chunks"] = chunks
            if _COMMIT_HOOK is not None:
                _COMMIT_HOOK(self.name, operation, version)
            # optimistic concurrency: publish is create-exclusive, so a
            # concurrent writer that claimed the same version fails loudly
            # instead of silently last-writer-winning (Iceberg's
            # commit-conflict guarantee). With a catalog the publish routes
            # through it — fence-checked, and on the tcp backend serialized
            # + WAL-journaled by the coordinator; without one it is the
            # PR-10 direct path (see io/fs.py put_if_absent for the
            # local-atomic vs remote-best-effort split).
            if self.catalog is not None:
                try:
                    epoch = self._writer_epoch()
                    # keep the writer lease live across the rebase loop so
                    # a long conflict storm can't expire us into the fence
                    self.catalog.writer_renew(
                        self, self._writer_token,
                        resolve_writer_ttl(self.conf),
                    )
                    published = self.catalog.commit(
                        self, manifest, epoch=epoch
                    )
                except CatalogFencedError as exc:
                    # a vacuum fenced this writer (lease expired — zombie
                    # presumption) and may have reclaimed its stage: the
                    # whole transaction must re-run with a fresh epoch and
                    # fresh staged files. CommitConflictError routes it to
                    # the ladder's commit_rebase_retry rung.
                    self._release_writer()
                    raise CommitConflictError(
                        f"{self.path}: {exc} (re-run the transaction)"
                    ) from exc
            else:
                tmp = posixpath.join(
                    self.manifest_dir,
                    f".tmp-{os.getpid()}-{uuid.uuid4().hex}.json",
                )
                with self.fs.open(tmp, "w") as fh:
                    json.dump(manifest, fh)
                dest = posixpath.join(
                    self.manifest_dir, f"v{version:06d}.json"
                )
                published = put_if_absent(self.fs, tmp, dest)
            if published:
                self._release_writer()
                tracer = _tracer()
                if tracer is not None:
                    tracer.emit(
                        "lake_commit", table=self.name, operation=operation,
                        version=version, attempts=attempts,
                        rebased=attempts > 1,
                    )
                return version
            # lost the race. Overwrite-style transactions (explicit base
            # file list) abort: their writes no longer describe the head.
            delay = (
                next(delays, None) if base_files is None else None
            )
            if delay is None:
                tracer = _tracer()
                if tracer is not None:
                    tracer.emit(
                        "lake_commit", table=self.name, operation=operation,
                        version=version, attempts=attempts, conflict=True,
                    )
                why = (
                    f"rebase budget ({retries}) exhausted"
                    if base_files is None
                    else "overwrite transactions cannot rebase"
                )
                # drop the writer lease HERE, not only in _discard_staged:
                # rollback transactions reach this raise with no staged
                # files and would otherwise pin the fence for the full
                # writer TTL (idempotent — the discard path re-calls it)
                self._release_writer()
                raise CommitConflictError(
                    f"{self.path}: concurrent commit conflict at version "
                    f"{version} after {attempts} attempt(s) ({why}); "
                    f"retry the transaction"
                )
            if delay:
                time.sleep(delay)

    def append(self, table, operation="append") -> int:
        """INSERT: add rows (pa.Table or batch iterable) as new immutable
        files; returns the new version. Concurrent appends converge via
        commit rebase (both row sets present)."""
        staged = self._stage(table)
        try:
            return self._commit(staged, operation)
        except CommitConflictError:
            self._discard_staged(staged)
            raise

    def replace(self, table: pa.Table, operation="overwrite") -> int:
        """Replace the full file set (copy-on-write DELETE/UPDATE). Aborts
        on ANY concurrent commit — the replacement rows were derived from a
        snapshot that is no longer the head."""
        staged = self._stage(table)
        try:
            return self._commit(
                staged, operation, base_files=[],
                num_rows=sum(s[1] for s in staged),
            )
        except CommitConflictError:
            self._discard_staged(staged)
            raise

    def ingest_chunk(self, tbl, chunk_id: str, cluster_by=None,
                     max_file_bytes=None):
        """Exactly-once chunk append for parallel ingest: stage `tbl`
        clustered on `cluster_by`, then commit with `chunk_id` recorded
        in the manifest's ingest ledger. The ledger IS the checkpoint —
        a killed worker's resume replays its chunks, the commit point
        skips any id already in the head ledger, and staged files from
        the un-published attempt are below-fence debris for vacuum.
        Returns the published version, or None when the chunk was
        already ingested (nothing committed, stage discarded)."""
        if chunk_id in self.snapshot().ingest_chunks():
            return None  # cheap pre-flight; the commit point re-checks
        staged = self.stage_clustered(tbl, cluster_by, max_file_bytes)
        try:
            return self._commit(staged, "ingest", new_chunks=[chunk_id])
        except _ChunkAlreadyIngested:
            self._discard_staged(staged)
            return None
        except CommitConflictError:
            self._discard_staged(staged)
            raise

    # -- time travel -------------------------------------------------------
    def rollback_to_version(self, version: int) -> int:
        m = self._manifest(version)
        return self._commit(
            [], f"rollback-to-v{version}", base_files=m["files"],
            num_rows=m.get("num_rows"), base_stats=m.get("stats"),
            base_chunks=m.get("ingest_chunks"),
        )

    def rollback_to_timestamp(self, ts_ms: int) -> int:
        """Roll back to the last snapshot at or before ts_ms (reference:
        CALL spark_catalog.system.rollback_to_timestamp, nds_rollback.py:46-51).
        A ts_ms exactly equal to a snapshot's (strictly monotonic)
        timestamp selects that snapshot."""
        candidates = [v for v, t, _ in self.versions() if t <= ts_ms]
        if not candidates:
            raise LakehouseError(
                f"{self.path}: no snapshot at or before {ts_ms}"
            )
        return self.rollback_to_version(max(candidates))

    # -- maintenance: compaction (OPTIMIZE) --------------------------------
    def compact(self, target_bytes=None, min_input_files=None) -> dict:
        """Small-file rewrite (Iceberg's rewrite_data_files / OPTIMIZE):
        coalesce files below `target_bytes` into ~target-sized ones so
        parallel ingest's per-chunk commits don't permanently fragment
        the layout. Logical content is untouched — num_rows, the ingest
        ledger, and untouched files' stats carry over; the rewritten
        files get FRESH zone maps from `_stage` (the merged file's real
        bounds, not a union of its inputs').

        Runs as an explicit-base transaction: the commit publishes only
        if the head is still the snapshot the rewrite read, otherwise it
        aborts with CommitConflictError (a concurrent append's rows must
        not be dropped) — callers retry the whole pass, as in
        maintenance._run_dm_statement. Concurrent snapshot-pinned readers
        are unaffected: the input files stay referenced by retained
        manifests (and reader leases) until vacuum.

        Returns {"files_in", "files_out", "bytes_in", "version"};
        version None means nothing worth rewriting."""
        target_bytes = (
            resolve_compact_target_bytes(self.conf)
            if target_bytes is None else int(target_bytes)
        )
        if min_input_files is None:
            min_input_files = resolve_compact_min_files(self.conf)
        snap = self.snapshot()
        sizes = {}
        for rel in snap.rel_files:
            try:
                info = self.fs.info(posixpath.join(self.root, rel))
                sizes[rel] = int(info.get("size") or target_bytes)
            except OSError:
                sizes[rel] = target_bytes  # unreadable: never a candidate
        small = [r for r in snap.rel_files if sizes[r] < target_bytes]
        if len(small) < max(int(min_input_files), 2):
            return {"table": self.name, "files_in": 0, "files_out": 0,
                    "bytes_in": 0, "version": None}
        # bin-pack in manifest order — ingest commits append key-clustered
        # files in arrival order, so neighbors usually share a key range
        # and the merged file keeps a tight zone map
        groups, cur_group, cur_bytes = [], [], 0
        for rel in small:
            cur_group.append(rel)
            cur_bytes += sizes[rel]
            if cur_bytes >= target_bytes:
                groups.append(cur_group)
                cur_group, cur_bytes = [], 0
        if len(cur_group) >= 2:
            groups.append(cur_group)
        groups = [g for g in groups if len(g) >= 2]
        if not groups:
            return {"table": self.name, "files_in": 0, "files_out": 0,
                    "bytes_in": 0, "version": None}
        staged, inputs = [], []
        try:
            for g in groups:
                merged = snap.dataset(files=g).to_table()
                staged.extend(self._stage(merged, schema=merged.schema))
                inputs.extend(g)
            replaced = set(inputs)
            base = [r for r in snap.rel_files if r not in replaced]
            stats = snap.file_stats()
            version = self._commit(
                staged, "optimize", base_files=base,
                num_rows=snap.manifest.get("num_rows"),
                base_stats={r: stats[r] for r in base if r in stats},
                base_chunks=snap.manifest.get("ingest_chunks"),
            )
        except Exception:
            self._discard_staged(staged)
            raise
        return {
            "table": self.name,
            "files_in": len(inputs),
            "files_out": len(staged),
            "bytes_in": sum(sizes[r] for r in inputs),
            "version": version,
        }

    # -- maintenance: snapshot expiry + vacuum -----------------------------
    def _retain_last(self, retain_last) -> int:
        if retain_last is None and self.conf:
            retain_last = self.conf.get("engine.lake_vacuum_retain")
        if retain_last is None:
            retain_last = os.environ.get("NDS_LAKE_VACUUM_RETAIN")
        try:
            return max(int(retain_last), 1) if retain_last else 2
        except (TypeError, ValueError):
            return 2

    def expire_snapshots(self, retain_last=None, older_than_ms=None):
        """Drop old manifests (Iceberg's expire_snapshots). The head and
        the newest `retain_last` versions always survive, as does any
        version a live reader lease pins (its manifest stays resolvable
        for rollback while the reader works; the lease's own FILE list
        protects data either way). Returns the expired version numbers."""
        vs = self.versions()
        retain_last = self._retain_last(retain_last)
        keep = {v for v, _, _ in vs[-retain_last:]}
        leased = self._held_versions()
        expired = []
        for v, ts, _ in vs:
            if v in keep or v in leased:
                continue
            if older_than_ms is not None and ts > older_than_ms:
                continue
            try:
                self.fs.rm_file(
                    posixpath.join(self.manifest_dir, f"v{v:06d}.json")
                )
            except OSError:
                continue  # already gone / transient: next vacuum retries
            expired.append(v)
        return expired

    def vacuum(self, retain_last=None, older_than_ms=None) -> dict:
        """Expire old snapshots, then delete data files that no retained
        manifest references — EXCEPT files covered by a live reader lease
        (a pinned query may still be scanning an expired snapshot) or
        staged by a live writer pid (an in-flight commit's files are not
        orphans). Crash-safe by ordering: manifests are removed before
        their files, so an interrupted vacuum leaves only sweepable
        unreferenced files, never a manifest pointing at deleted data."""
        from .. import faults

        if faults.active():
            # io/crash injection site: a crash mid-vacuum must never lose
            # a committed snapshot (retained manifests + their files are
            # untouched by construction)
            faults.maybe_fire(f"vacuum:{self.name}", kinds=("io", "crash"))
        # capture the pre-expiry referenced set FIRST: a file some manifest
        # references was committed, so once its manifest expires it is
        # collectable even though its writer pid is still alive — the
        # live-pid guard below is only for never-referenced in-flight
        # stages (a commit racing this vacuum)
        committed = self._all_referenced_files()
        expired = self.expire_snapshots(retain_last, older_than_ms)
        referenced = self._all_referenced_files()
        leased = self._held_files()
        # epoch fencing (catalog mode): advance the fence to the minimum
        # LIVE writer epoch BEFORE collecting. Any never-referenced stage
        # with epoch < fence belongs to a writer whose publish is now
        # refused at the catalog, so deleting it can never tear a commit —
        # the cross-host replacement for pid-liveness attribution, and the
        # close of PR-10's publish-vs-unlink window (airtight on the tcp
        # backend, rename-narrowed on fs).
        fence = None
        if self.catalog is not None:
            fence = self.catalog.bump_fence(self)
            self.catalog.sweep_expired(self)
        removed, leased_kept, bytes_removed = [], 0, 0
        try:
            entries = self.fs.ls(self.data_dir, detail=True)
        except OSError:
            entries = []
        # re-read the manifest log AFTER the fence bump and the data-dir
        # listing: a commit that published between the first referenced-set
        # read and the listing (a racing writer that then exited, defeating
        # the pid-liveness guard) must land in `referenced` before anything
        # is deleted. Without a catalog the residual publish-vs-unlink
        # window is the one Iceberg closes with a catalog service —
        # configure `engine.lake_catalog` to close it here too.
        referenced |= self._all_referenced_files()
        for ent in entries:
            full = ent["name"] if isinstance(ent, dict) else str(ent)
            base = posixpath.basename(full)
            if not _DATA_FILE_RE.match(base):
                continue  # never touch files outside our naming scheme
            rel = posixpath.join(_DATA_DIR, base)
            if rel in referenced:
                continue
            if rel in leased:
                leased_kept += 1
                continue
            m = _STAGED_RE.match(base)
            if rel not in committed and m is not None:
                if fence is not None and m.group(2) is not None:
                    # epoch-attributed stage: protected while its epoch is
                    # at/above the fence (a live writer's in-flight commit);
                    # below it the writer is fenced — collectable anywhere
                    if int(m.group(2)) >= fence:
                        continue
                elif not self._is_local() or _pid_alive(int(m.group(1))):
                    # pid attribution is host-local, so without a catalog a
                    # REMOTE (shared) warehouse protects every never-
                    # referenced stage unconditionally — deleting a live
                    # remote writer's stage would corrupt the commit it is
                    # about to publish.
                    continue
            if faults.active():
                faults.maybe_fire_path(full)
            try:
                self.fs.rm_file(posixpath.join(self.data_dir, base))
            except OSError:
                continue
            removed.append(rel)
            if isinstance(ent, dict):
                bytes_removed += int(ent.get("size") or 0)
        tracer = _tracer()
        if tracer is not None:
            tracer.emit(
                "lake_vacuum", table=self.name, files_removed=len(removed),
                manifests_removed=len(expired), files_leased=leased_kept,
                bytes_removed=bytes_removed,
            )
        return {
            "table": self.name,
            "files_removed": len(removed),
            "manifests_removed": len(expired),
            "files_leased": leased_kept,
            "bytes_removed": bytes_removed,
            "removed": removed,
            "expired_versions": expired,
        }

    def _all_referenced_files(self) -> set:
        """Union of every live manifest's file list; a manifest expiring
        between the listing and its read is skipped (post-expiry view)."""
        out = set()
        for v in self._version_numbers():
            try:
                out.update(self._manifest(v)["files"])
            except LakehouseError:
                continue  # expired under us
        return out

    # -- crash hygiene: orphaned-stage sweep -------------------------------
    def sweep_orphans(self) -> int:
        """Remove a crashed writer's leavings: staged data files that no
        manifest references and whose embedded writer pid is dead, plus
        torn `.tmp-<pid>-*.json` manifest temps with dead pids. Files the
        naming scheme cannot attribute (foreign files, pre-pid-format
        parts) are never touched — the same never-touch-foreign contract
        as spill.sweep_orphans. Pid liveness is host-local, so WITHOUT a
        catalog a REMOTE (shared) warehouse sweep is a no-op — a live
        writer on another host would read as dead and lose its in-flight
        stage. With `engine.lake_catalog` configured, epoch-stamped
        stages below the table's fence are sweepable on ANY store (their
        writers can never publish), which is how remote deployments get
        crash hygiene back. Returns the number of files removed."""
        fence = self.catalog.read_fence(self) if self.catalog else None
        if not self._is_local() and fence is None:
            return 0
        referenced = self._all_referenced_files()
        removed = 0
        try:
            data_names = [
                posixpath.basename(f)
                for f in self.fs.ls(self.data_dir, detail=False)
            ]
        except OSError:
            data_names = []
        for base in data_names:
            m = _STAGED_RE.match(base)
            if m is None:
                continue
            if posixpath.join(_DATA_DIR, base) in referenced:
                continue
            if fence is not None and m.group(2) is not None:
                # fence attribution works cross-host: below the fence the
                # writer is refused at publish, so its stage is debris
                if int(m.group(2)) >= fence:
                    continue
            elif not self._is_local():
                continue  # unattributable remotely without an epoch
            else:
                pid = int(m.group(1))
                if pid == os.getpid() or _pid_alive(pid):
                    continue
            try:
                self.fs.rm_file(posixpath.join(self.data_dir, base))
                removed += 1
            except OSError:
                pass
        try:
            man_names = [
                posixpath.basename(f)
                for f in self.fs.ls(self.manifest_dir, detail=False)
            ]
        except OSError:
            man_names = []
        for base in man_names:
            m = _TMP_MANIFEST_RE.match(base)
            if m is None:
                continue
            if not self._is_local():
                # tmp manifests carry no epoch: pid attribution only, and
                # only where pids mean something (they are tiny debris on
                # remote stores, never a correctness hazard)
                continue
            pid = int(m.group(1))
            if pid == os.getpid() or _pid_alive(pid):
                continue
            try:
                self.fs.rm_file(posixpath.join(self.manifest_dir, base))
                removed += 1
            except OSError:
                pass
        if removed:
            print(
                f"lakehouse: swept {removed} orphaned file(s) from "
                f"{self.path}"
            )
        return removed


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but owned elsewhere: treat as alive
    return True


# one sweep per (process, table root): sessions are per-stream in
# throughput runs, and re-listing every table per session buys nothing.
# Process-lifetime once-latch; worst case under a race is a second,
# idempotent sweep.
# nds-lint: disable=mutable-module-global
_SWEPT_TABLES = set()


def sweep_table_at_session_start(path: str):
    """Session-start crash hygiene for one lakehouse table (called by the
    catalog when a lakehouse entry is registered): remove a dead writer's
    staged-but-uncommitted data files and torn manifest temps, once per
    process per table."""
    key = str(path)
    if key in _SWEPT_TABLES:
        return 0
    _SWEPT_TABLES.add(key)
    try:
        if not LakehouseTable.is_table(path):
            return 0
        return LakehouseTable(path).sweep_orphans()
    except Exception:
        return 0  # hygiene is best-effort; never block a session build
