"""Snapshot-manifest table layer: the Iceberg/Delta-equivalent ACID surface.

The reference runs Data Maintenance against Iceberg or Delta Lake warehouses
(reference: nds/nds_maintenance.py:118-202, nds/nds_rollback.py:46-51). The
TPU framework needs the same capabilities — atomic INSERT/DELETE, snapshot
history, timestamp rollback — without a JVM catalog service. This layer
provides them with immutable parquet data files plus a JSON manifest log:

    <table>/
      data/part-<version>-<n>.parquet      (immutable)
      _manifests/v000001.json ...          (one per snapshot)

A snapshot lists the data files that constitute the table at that version.
Writers stage data files first, then commit by publishing the next manifest
(create-exclusive), so readers always see a consistent snapshot. Rollback
appends a new manifest replaying an older file list — history is never
rewritten, matching Iceberg's rollback_to_timestamp semantics.

All IO routes through the fsspec seam (io/fs.py), so a table may live on a
local path, memory:// (tests), or any cloud URL — the reference reaches
HDFS/S3/GS in every phase and a multi-host run needs a shared warehouse.
"""

from __future__ import annotations

import json
import posixpath
import time
import uuid

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..io.fs import get_fs, put_if_absent

_MANIFEST_DIR = "_manifests"
_DATA_DIR = "data"


class LakehouseError(Exception):
    pass


class LakehouseTable:
    def __init__(self, path: str):
        self.path = str(path)
        self.fs, self.root = get_fs(path)
        self.manifest_dir = posixpath.join(self.root, _MANIFEST_DIR)
        self.data_dir = posixpath.join(self.root, _DATA_DIR)
        if not self.fs.isdir(self.manifest_dir):
            raise LakehouseError(f"{path} is not a lakehouse table")

    # -- creation ----------------------------------------------------------
    @classmethod
    def create(cls, path: str, batches=None, schema: pa.Schema | None = None):
        """Create an empty table (or one seeded from an iterable of record
        batches / a pa.Table)."""
        fs, root = get_fs(path)
        fs.makedirs(posixpath.join(root, _MANIFEST_DIR), exist_ok=True)
        fs.makedirs(posixpath.join(root, _DATA_DIR), exist_ok=True)
        t = cls(path)
        staged = t._stage(batches, schema) if batches is not None else []
        if schema is None and staged:
            with t.fs.open(posixpath.join(t.root, staged[0][0]), "rb") as fh:
                schema = pq.read_schema(fh)
        t._commit(staged, "create", base_files=[], schema=schema)
        return t

    @classmethod
    def is_table(cls, path: str) -> bool:
        fs, root = get_fs(path)
        return fs.isdir(posixpath.join(root, _MANIFEST_DIR))

    # -- snapshot log ------------------------------------------------------
    def versions(self):
        """[(version, timestamp_ms, operation)] ascending."""
        out = []
        for f in sorted(self.fs.ls(self.manifest_dir, detail=False)):
            name = posixpath.basename(f)
            if name.startswith("v") and name.endswith(".json"):
                with self.fs.open(f, "r") as fh:
                    m = json.load(fh)
                out.append((m["version"], m["timestamp_ms"], m["operation"]))
        return out

    def _manifest(self, version: int) -> dict:
        p = posixpath.join(self.manifest_dir, f"v{version:06d}.json")
        try:
            with self.fs.open(p, "r") as fh:
                return json.load(fh)
        except FileNotFoundError:
            raise LakehouseError(f"{self.path}: no snapshot v{version}")

    def current_version(self) -> int:
        vs = [v for v, _, _ in self.versions()]
        if not vs:
            raise LakehouseError(f"{self.path}: no snapshots")
        return max(vs)

    def current_files(self):
        m = self._manifest(self.current_version())
        return [posixpath.join(self.root, f) for f in m["files"]]

    def num_rows(self) -> int:
        m = self._manifest(self.current_version())
        return m.get("num_rows", -1)

    # -- reads -------------------------------------------------------------
    def dataset(self) -> pads.Dataset:
        files = self.current_files()
        if not files:
            # empty snapshot: in-memory empty dataset over the stored schema
            schema = self.schema()
            if schema is None:
                raise LakehouseError(f"{self.path}: empty table with no schema")
            return pads.dataset(schema.empty_table())
        return pads.dataset(files, format="parquet", filesystem=self.fs)

    def schema(self) -> pa.Schema | None:
        files = self.current_files()
        if files:
            with self.fs.open(files[0], "rb") as fh:
                return pq.read_schema(fh)
        m = self._manifest(self.current_version())
        if m.get("schema_hex"):
            # an all-rows DELETE leaves zero data files; the manifest still
            # carries the schema so the table stays readable
            import pyarrow.ipc as ipc

            return ipc.read_schema(
                pa.BufferReader(bytes.fromhex(m["schema_hex"]))
            )
        return None

    # -- writes ------------------------------------------------------------
    def _stage(self, batches, schema=None):
        """Write data files; returns [(relpath, num_rows)]. Not yet visible."""
        if isinstance(batches, pa.Table):
            batches = batches.to_batches(max_chunksize=1 << 20)
        staged = []
        writer = None
        out = None
        relpath = None
        n_rows = 0
        try:
            for b in batches:
                if writer is None:
                    relpath = posixpath.join(
                        _DATA_DIR, f"part-{uuid.uuid4().hex[:12]}.parquet"
                    )
                    out = self.fs.open(
                        posixpath.join(self.root, relpath), "wb"
                    )
                    writer = pq.ParquetWriter(
                        out, schema or b.schema, compression="snappy"
                    )
                writer.write_batch(b)
                n_rows += b.num_rows
        finally:
            if writer is not None:
                writer.close()
            if out is not None:
                out.close()
        if relpath is not None:
            staged.append((relpath, n_rows))
        return staged

    def _commit(self, staged, operation, base_files=None, num_rows=None, schema=None):
        """Append the next manifest: base file list + staged files."""
        from .. import faults

        if faults.active():
            # failure-domain injection site: a fault here lands BEFORE the
            # manifest publish, so staged data files exist but no snapshot
            # references them — proving commits are all-or-nothing under
            # io/crash faults (Iceberg's commit-point guarantee)
            faults.maybe_fire(f"commit:{posixpath.basename(self.root)}")
            faults.maybe_fire_path(self.root)
        schema_hex = None
        if schema is not None:
            schema_hex = bytes(schema.serialize()).hex()
        try:
            cur = self._manifest(self.current_version())
            version = cur["version"] + 1
            base = cur["files"] if base_files is None else base_files
            base_rows = cur.get("num_rows", 0) if base_files is None else 0
            prev_ts = cur["timestamp_ms"]
            if schema_hex is None:
                schema_hex = cur.get("schema_hex")
        except LakehouseError:
            version, base, base_rows, prev_ts = 1, base_files or [], 0, 0
        files = list(base) + [p for p, _ in staged]
        total = (
            num_rows
            if num_rows is not None
            else base_rows + sum(n for _, n in staged)
        )
        manifest = {
            "version": version,
            # strictly monotonic so timestamp rollback can never tie between
            # adjacent snapshots (Iceberg has the same guarantee)
            "timestamp_ms": max(int(time.time() * 1000), prev_ts + 1),
            "operation": operation,
            "files": files,
            "num_rows": total,
            "schema_hex": schema_hex,
        }
        tmp = posixpath.join(self.manifest_dir, f".tmp-{uuid.uuid4().hex}.json")
        with self.fs.open(tmp, "w") as fh:
            json.dump(manifest, fh)
        # optimistic concurrency: publish is create-exclusive, so a
        # concurrent writer that claimed the same version fails loudly
        # instead of silently last-writer-winning (Iceberg's
        # commit-conflict guarantee; see io/fs.py put_if_absent for the
        # local-atomic vs remote-best-effort split)
        dest = posixpath.join(self.manifest_dir, f"v{version:06d}.json")
        if not put_if_absent(self.fs, tmp, dest):
            raise LakehouseError(
                f"{self.path}: concurrent commit conflict at version "
                f"{version}; retry the transaction"
            )
        return version

    def append(self, table, operation="append") -> int:
        """INSERT: add rows (pa.Table or batch iterable) as new immutable
        files; returns the new version."""
        staged = self._stage(table)
        return self._commit(staged, operation)

    def replace(self, table: pa.Table, operation="overwrite") -> int:
        """Replace the full file set (copy-on-write DELETE/UPDATE)."""
        staged = self._stage(table)
        return self._commit(
            staged, operation, base_files=[],
            num_rows=sum(n for _, n in staged),
        )

    # -- time travel -------------------------------------------------------
    def rollback_to_version(self, version: int) -> int:
        m = self._manifest(version)
        return self._commit(
            [], f"rollback-to-v{version}", base_files=m["files"],
            num_rows=m.get("num_rows"),
        )

    def rollback_to_timestamp(self, ts_ms: int) -> int:
        """Roll back to the last snapshot at or before ts_ms (reference:
        CALL spark_catalog.system.rollback_to_timestamp, nds_rollback.py:46-51)."""
        candidates = [v for v, t, _ in self.versions() if t <= ts_ms]
        if not candidates:
            raise LakehouseError(
                f"{self.path}: no snapshot at or before {ts_ms}"
            )
        return self.rollback_to_version(max(candidates))
