"""In-process reader-lease table: the snapshot pins vacuum must respect.

Iceberg/Delta get reader safety from their catalogs: `expire_snapshots`
never deletes a data file a live reader's snapshot still references,
because readers resolve snapshots through the same catalog service
(reference: nds/nds_maintenance.py:118-202 runs snapshot expiry against
exactly such a catalog). This engine has no catalog service — readers pin
manifest versions in-process (engine/session.py resolves each lake scan's
version once at plan time), so the equivalent safety record lives here: a
process-wide table of (table root, version, file list) leases with a TTL.

`LakehouseTable.vacuum` consults `held_files` and never deletes a file a
live lease covers; `expire_snapshots` keeps leased versions' manifests.
Leases record the snapshot's FILE LIST at acquire time, so even a lease
whose manifest has since been expired keeps protecting its files.

The TTL (conf `engine.lake_lease_ttl_s` / env NDS_LAKE_LEASE_TTL_S,
default 300 s) bounds leakage: a crashed or abandoned reader's lease
expires instead of pinning files forever. Pins renew on re-resolution, so
a healthy long query stream never loses its lease mid-run. The table is
process-wide on purpose — concurrent streams (thread-mode throughput,
the maintenance-under-load phase) share one lease table exactly like
they share one fault registry; cross-process vacuum safety is the TTL's
job (vacuum only races readers inside the maintenance window, and the
reference's single-catalog deployments have the same process scope).
"""

from __future__ import annotations

import itertools
import os
import threading
from time import monotonic as _monotonic
from ..engine.lockdebug import make_lock

#: default reader-lease TTL in seconds (engine.lake_lease_ttl_s /
#: NDS_LAKE_LEASE_TTL_S): long enough for any benchmarked query, short
#: enough that a crashed reader never blocks vacuum for more than one
#: maintenance window
DEFAULT_LEASE_TTL_S = 300.0


def resolve_lease_ttl(conf: dict | None = None) -> float:
    v = None
    if conf:
        v = conf.get("engine.lake_lease_ttl_s")
    if v is None:
        v = os.environ.get("NDS_LAKE_LEASE_TTL_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else (
            DEFAULT_LEASE_TTL_S
        )
    except (TypeError, ValueError):
        return DEFAULT_LEASE_TTL_S


class ReaderLeases:
    """Thread-safe lease table. Leases are cheap dict records; expired
    entries are pruned lazily on every read/write, so an idle process
    holds at most the leases of its last activity burst."""

    def __init__(self):
        self._lock = make_lock("ReaderLeases._lock")
        self._ids = itertools.count(1)
        self._leases = {}  # id -> lease record  # nds-guarded-by: _lock

    def acquire(self, root: str, version: int, files, ttl_s: float,
                remote=None) -> int:
        """Register a pin of `version` over `files` (manifest-relative
        paths) of the table at `root`; returns the lease id. `remote` is
        an optional catalog lease handle (lakehouse/catalog.py
        RemoteLease): when present, renew/release forward to it — this
        table is then the local cache of catalog state, and vacuum on
        OTHER hosts sees the catalog half."""
        lease_id = next(self._ids)
        rec = {
            "root": str(root),
            "version": int(version),
            "files": frozenset(str(f) for f in files),
            "expires": _monotonic() + float(ttl_s),
            "remote": remote,
        }
        with self._lock:
            self._prune_locked(_monotonic())
            self._leases[lease_id] = rec
        return lease_id

    def renew(self, lease_id: int, ttl_s: float) -> bool:
        """Extend a live lease; False when it already expired/was released
        (caller should re-acquire). Forwards to the catalog half when the
        lease was written through — THROTTLED to once per ttl/3 (with a
        short failure backoff), because renew() runs on the memwatch
        heartbeat thread and a blocking remote call every beat would
        stall the OOM-watermark sampling the thread exists for. A missed
        remote renewal falls back to the remote TTL, never blocks the
        local pin."""
        now = _monotonic()
        with self._lock:
            self._prune_locked(now)
            rec = self._leases.get(lease_id)
            if rec is None:
                return False
            rec["expires"] = now + float(ttl_s)
            remote = rec.get("remote")
            if remote is not None and now < rec.get("remote_next", 0.0):
                remote = None  # remote half renewed recently enough
            if remote is not None:
                # claim the slot BEFORE the (unlocked) network call so
                # concurrent renewers don't pile onto a slow coordinator
                rec["remote_next"] = now + max(float(ttl_s) / 3.0, 0.05)
        if remote is not None:
            try:
                if not remote.renew(ttl_s):
                    raise OSError("remote lease renewal refused")
            except Exception:
                # remote TTL is the backstop; back off so a down
                # coordinator costs at most one short timeout per 5s
                with self._lock:
                    rec = self._leases.get(lease_id)
                    if rec is not None:
                        rec["remote_next"] = _monotonic() + 5.0
        return True

    def release(self, lease_id: int) -> bool:
        with self._lock:
            rec = self._leases.pop(lease_id, None)
        if rec is not None and rec.get("remote") is not None:
            try:
                rec["remote"].release()
            except Exception:
                pass  # remote TTL expiry is the backstop
        return rec is not None

    def _prune_locked(self, now: float):
        dead = [i for i, r in self._leases.items() if r["expires"] <= now]
        for i in dead:
            del self._leases[i]

    # -- vacuum-side reads -------------------------------------------------
    def held_versions(self, root: str) -> set:
        root = str(root)
        with self._lock:
            self._prune_locked(_monotonic())
            return {
                r["version"] for r in self._leases.values()
                if r["root"] == root
            }

    def held_files(self, root: str) -> set:
        """Manifest-relative file paths any live lease on `root` covers."""
        root = str(root)
        out = set()
        with self._lock:
            self._prune_locked(_monotonic())
            for r in self._leases.values():
                if r["root"] == root:
                    out |= r["files"]
        return out

    def live_count(self, root: str | None = None) -> int:
        with self._lock:
            self._prune_locked(_monotonic())
            if root is None:
                return len(self._leases)
            root = str(root)
            return sum(
                1 for r in self._leases.values() if r["root"] == root
            )


#: the process-wide lease table (module singleton, like faults._registry):
#: every session's pins and every table's vacuum meet here
LEASES = ReaderLeases()
