"""In-process reader-lease table: the snapshot pins vacuum must respect.

Iceberg/Delta get reader safety from their catalogs: `expire_snapshots`
never deletes a data file a live reader's snapshot still references,
because readers resolve snapshots through the same catalog service
(reference: nds/nds_maintenance.py:118-202 runs snapshot expiry against
exactly such a catalog). This engine has no catalog service — readers pin
manifest versions in-process (engine/session.py resolves each lake scan's
version once at plan time), so the equivalent safety record lives here: a
process-wide table of (table root, version, file list) leases with a TTL.

`LakehouseTable.vacuum` consults `held_files` and never deletes a file a
live lease covers; `expire_snapshots` keeps leased versions' manifests.
Leases record the snapshot's FILE LIST at acquire time, so even a lease
whose manifest has since been expired keeps protecting its files.

The TTL (conf `engine.lake_lease_ttl_s` / env NDS_LAKE_LEASE_TTL_S,
default 300 s) bounds leakage: a crashed or abandoned reader's lease
expires instead of pinning files forever. Pins renew on re-resolution, so
a healthy long query stream never loses its lease mid-run. The table is
process-wide on purpose — concurrent streams (thread-mode throughput,
the maintenance-under-load phase) share one lease table exactly like
they share one fault registry; cross-process vacuum safety is the TTL's
job (vacuum only races readers inside the maintenance window, and the
reference's single-catalog deployments have the same process scope).
"""

from __future__ import annotations

import itertools
import os
import threading
from time import monotonic as _monotonic

#: default reader-lease TTL in seconds (engine.lake_lease_ttl_s /
#: NDS_LAKE_LEASE_TTL_S): long enough for any benchmarked query, short
#: enough that a crashed reader never blocks vacuum for more than one
#: maintenance window
DEFAULT_LEASE_TTL_S = 300.0


def resolve_lease_ttl(conf: dict | None = None) -> float:
    v = None
    if conf:
        v = conf.get("engine.lake_lease_ttl_s")
    if v is None:
        v = os.environ.get("NDS_LAKE_LEASE_TTL_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else (
            DEFAULT_LEASE_TTL_S
        )
    except (TypeError, ValueError):
        return DEFAULT_LEASE_TTL_S


class ReaderLeases:
    """Thread-safe lease table. Leases are cheap dict records; expired
    entries are pruned lazily on every read/write, so an idle process
    holds at most the leases of its last activity burst."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._leases = {}  # id -> {root, version, files, expires}

    def acquire(self, root: str, version: int, files, ttl_s: float) -> int:
        """Register a pin of `version` over `files` (manifest-relative
        paths) of the table at `root`; returns the lease id."""
        lease_id = next(self._ids)
        rec = {
            "root": str(root),
            "version": int(version),
            "files": frozenset(str(f) for f in files),
            "expires": _monotonic() + float(ttl_s),
        }
        with self._lock:
            self._prune(_monotonic())
            self._leases[lease_id] = rec
        return lease_id

    def renew(self, lease_id: int, ttl_s: float) -> bool:
        """Extend a live lease; False when it already expired/was released
        (caller should re-acquire)."""
        now = _monotonic()
        with self._lock:
            self._prune(now)
            rec = self._leases.get(lease_id)
            if rec is None:
                return False
            rec["expires"] = now + float(ttl_s)
            return True

    def release(self, lease_id: int) -> bool:
        with self._lock:
            return self._leases.pop(lease_id, None) is not None

    def _prune(self, now: float):
        dead = [i for i, r in self._leases.items() if r["expires"] <= now]
        for i in dead:
            del self._leases[i]

    # -- vacuum-side reads -------------------------------------------------
    def held_versions(self, root: str) -> set:
        root = str(root)
        with self._lock:
            self._prune(_monotonic())
            return {
                r["version"] for r in self._leases.values()
                if r["root"] == root
            }

    def held_files(self, root: str) -> set:
        """Manifest-relative file paths any live lease on `root` covers."""
        root = str(root)
        out = set()
        with self._lock:
            self._prune(_monotonic())
            for r in self._leases.values():
                if r["root"] == root:
                    out |= r["files"]
        return out

    def live_count(self, root: str | None = None) -> int:
        with self._lock:
            self._prune(_monotonic())
            if root is None:
                return len(self._leases)
            root = str(root)
            return sum(
                1 for r in self._leases.values() if r["root"] == root
            )


#: the process-wide lease table (module singleton, like faults._registry):
#: every session's pins and every table's vacuum meet here
LEASES = ReaderLeases()
