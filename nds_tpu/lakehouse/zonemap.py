"""Zone maps: per-file column statistics and the predicate logic that
prunes files against them.

Two halves, both deliberately dumb:

* **Write side** — `StatsAccumulator` streams over the Arrow batches a
  staged file is built from and reduces each column to
  ``{"min": v, "max": v, "nulls": n}``. The committer
  (`table.LakehouseTable._commit`) records the per-file result under the
  manifest's ``stats`` key, so the statistics travel WITH the snapshot:
  pruning against a pinned version uses that version's stats, never the
  head's (the same property that makes snapshot reads consistent makes
  zone-map pruning consistent).

* **Read side** — `prune_files` evaluates a conjunction of simple
  single-column predicates (extracted by the planner; this module never
  sees an expression tree) against those stats and returns the files
  that MAY contain matching rows. Every rule errs toward keeping: a
  file with no stats (old-format manifest), a column with no bounds, a
  type mismatch between bound and literal — all read as "may match".
  Pruning is an optimization, never a filter: the engine re-applies the
  full predicate to every surviving row, so a too-conservative zone map
  costs IO, a too-aggressive one would cost correctness. Only the
  conservative direction is reachable by construction.

Bounds are recorded only for JSON-safe, totally-ordered types (ints,
floats, bools, strings). Floats with a NaN min/max drop their bounds
entirely — NaN poisons interval reasoning (Iceberg records NaN counts
for the same reason). String bounds are truncated to
`_STR_BOUND_LIMIT` chars: a truncated *min* is already a valid lower
bound (a prefix sorts <= the full string); a truncated *max* must be
rounded UP past every string sharing the prefix, and when rounding up
is impossible (all chars at the codepoint ceiling) the max is dropped.
Null counts are always recorded: an all-null file (``nulls == rows``)
can be pruned by ANY null-rejecting predicate even when the column has
no bounds.

Predicates arrive as plain tuples so the evaluation stays import-light
and unit-testable without the planner:

    ("cmp", col, op, value)      op in =, <, <=, >, >=
    ("between", col, lo, hi)     inclusive both ends
    ("in", col, (v, ...))        non-empty literal list
    ("notnull", col)             IS NOT NULL
"""

from __future__ import annotations

import math

import pyarrow as pa
import pyarrow.compute as pc

# string min/max stored in the manifest are capped at this many chars;
# long bounds buy almost no pruning power and bloat every manifest
_STR_BOUND_LIMIT = 64

_MAX_CODEPOINT = 0x10FFFF


def _trunc_min(s: str) -> str:
    """A safe lower bound for a possibly-long string: its prefix (a
    prefix always sorts <= the full string)."""
    return s[:_STR_BOUND_LIMIT]


def _trunc_max(s: str):
    """A safe upper bound: round the truncated prefix UP so every string
    sharing it stays covered; None when no finite bound exists."""
    if len(s) <= _STR_BOUND_LIMIT:
        return s
    prefix = s[:_STR_BOUND_LIMIT]
    chars = list(prefix)
    while chars:
        cp = ord(chars[-1])
        if cp < _MAX_CODEPOINT:
            chars[-1] = chr(cp + 1)
            return "".join(chars)
        chars.pop()
    return None  # every char at the ceiling: unbounded above


def _boundable(typ: pa.DataType) -> bool:
    return (
        pa.types.is_integer(typ)
        or pa.types.is_floating(typ)
        or pa.types.is_boolean(typ)
        or pa.types.is_string(typ)
        or pa.types.is_large_string(typ)
    )


def _bad_bound(v) -> bool:
    return v is None or (isinstance(v, float) and math.isnan(v))


class StatsAccumulator:
    """Streaming per-column min/max/null reduction over record batches.
    One accumulator per staged FILE; `finish()` emits the manifest
    fragment for that file."""

    def __init__(self):
        self.rows = 0
        self._cols = {}  # name -> {"min","max","nulls","dead"}

    def update(self, batch):
        self.rows += batch.num_rows
        for i, field in enumerate(batch.schema):
            col = batch.column(i)
            st = self._cols.setdefault(
                field.name, {"min": None, "max": None, "nulls": 0,
                             "dead": not _boundable(field.type)}
            )
            st["nulls"] += col.null_count
            if st["dead"] or col.null_count == len(col):
                continue
            try:
                mm = pc.min_max(col)
                lo, hi = mm["min"].as_py(), mm["max"].as_py()
            except Exception:
                lo = hi = None
            try:
                inverted = lo > hi  # all-NaN floats reduce to (inf, -inf)
            except TypeError:
                inverted = True
            if _bad_bound(lo) or _bad_bound(hi) or inverted:
                # NaN (or an unreducible column) poisons the interval:
                # drop bounds for the whole file, keep counting nulls
                st["dead"] = True
                st["min"] = st["max"] = None
                continue
            if isinstance(lo, str):
                lo, hi = _trunc_min(lo), _trunc_max(hi)
                if hi is None:
                    st["dead"] = True
                    st["min"] = st["max"] = None
                    continue
            if st["min"] is None or lo < st["min"]:
                st["min"] = lo
            if st["max"] is None or hi > st["max"]:
                st["max"] = hi

    def finish(self) -> dict:
        """{"rows": n, "columns": {name: {"min","max","nulls"}}} with
        min/max omitted for unboundable columns (nulls always kept)."""
        cols = {}
        for name, st in self._cols.items():
            ent = {"nulls": int(st["nulls"])}
            if not st["dead"] and st["min"] is not None:
                ent["min"] = st["min"]
                ent["max"] = st["max"]
            cols[name] = ent
        return {"rows": int(self.rows), "columns": cols}


# ---------------------------------------------------------------------------
# read side: conjunct evaluation
# ---------------------------------------------------------------------------

def _comparable(bound, value) -> bool:
    """Bound/literal pairs we trust to compare with Python's < — both
    numeric (bool excluded: True == 1 is a trap) or both strings."""
    num = (int, float)
    if isinstance(bound, bool) or isinstance(value, bool):
        return isinstance(bound, bool) and isinstance(value, bool)
    if isinstance(bound, num) and isinstance(value, num):
        return True
    return isinstance(bound, str) and isinstance(value, str)


def _may_match_one(colstats: dict | None, rows: int, pred) -> bool:
    """May any row of a file with `colstats` for the predicate's column
    satisfy the predicate? Missing information always reads True."""
    if colstats is None:
        return True
    all_null = rows > 0 and int(colstats.get("nulls", 0)) >= rows
    kind = pred[0]
    if kind == "notnull":
        return not all_null
    # the remaining kinds are null-rejecting comparisons: an all-null
    # file cannot satisfy them whether or not bounds exist
    if all_null:
        return False
    lo, hi = colstats.get("min"), colstats.get("max")
    if lo is None or hi is None:
        return True
    if kind == "cmp":
        _, _, op, v = pred
        if not _comparable(lo, v):
            return True
        if op == "=":
            return lo <= v <= hi
        if op == "<":
            return lo < v
        if op == "<=":
            return lo <= v
        if op == ">":
            return hi > v
        if op == ">=":
            return hi >= v
        return True
    if kind == "between":
        _, _, plo, phi = pred
        if not (_comparable(lo, plo) and _comparable(lo, phi)):
            return True
        return not (hi < plo or lo > phi)
    if kind == "in":
        values = pred[2]
        if not values:
            return True
        for v in values:
            if not _comparable(lo, v):
                return True
            if lo <= v <= hi:
                return True
        return False
    return True


def file_may_match(file_stats: dict | None, preds) -> bool:
    """Evaluate a conjunction against one file's manifest stats entry
    (None = file has no stats = always keep)."""
    if not file_stats:
        return True
    rows = int(file_stats.get("rows", 0))
    cols = file_stats.get("columns") or {}
    for pred in preds:
        col = pred[1]
        if not _may_match_one(cols.get(col), rows, pred):
            return False
    return True


def prune_files(rel_files, stats: dict, preds):
    """Split a snapshot's file list against a conjunction of predicates.

    Returns ``(surviving_rel_files, pruned_rows)`` where pruned_rows is
    the EXACT row count of the pruned files (every prunable file has
    stats, so the count is known, which is what lets the budgeter turn
    it into a hard surviving-row upper bound)."""
    if not preds or not stats:
        return list(rel_files), 0
    keep, pruned_rows = [], 0
    for rel in rel_files:
        fstats = stats.get(rel)
        if file_may_match(fstats, preds):
            keep.append(rel)
        else:
            pruned_rows += int(fstats.get("rows", 0))
    return keep, pruned_rows
