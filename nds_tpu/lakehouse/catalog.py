"""Fleet catalog: cross-host commit arbitration, leases, and vacuum fencing.

PR 10 shipped a *process*-concurrent lakehouse and documented its two
residual limits: the publish-vs-unlink vacuum window ("closes with a
catalog service") and pid-liveness crash attribution gated to LOCAL
filesystems — on a shared/remote warehouse the sweep is a no-op and
multi-host writers are uncoordinated. This module is that catalog
service: a single-writer commit log owning version advancement, lease
registration, and vacuum fencing across hosts, with two interchangeable
backends behind one client API (`resolve_catalog`):

* **fs** (`engine.lake_catalog=fs`) — CAS over atomic rename on the
  warehouse itself. Zero extra processes: catalog state (fence, writer
  epochs, reader leases) lives in `<table>/_catalog/` next to the
  manifests, on any `io/fs.py` filesystem. Airtight where
  `put_if_absent` is genuinely atomic (local POSIX); on remote stores
  the commit CAS remains best-effort, narrowed by a fence re-check
  immediately before the publish rename.
* **tcp** (`engine.lake_catalog=http://host:port`) — a tiny coordinator
  process (`nds-tpu-submit catalog`) serializing every commit/lease/
  fence op for one warehouse under one lock, reusing the obs/httpserv.py
  single-listener pattern (the /catalog/* routes ride `attach_app` on
  the same port as /metrics + /statusz). Closes the CAS window
  completely — fence check, WAL append, and manifest publish are one
  critical section — and gives low-latency fleets one arbiter instead of
  N hopeful renamers.

**Epoch fencing (the zombie-writer contract).** Every writer registers
a TTL-bounded writer lease and receives a monotone *epoch* token; its
staged data files embed the epoch (`part-<pid>-e<epoch>-<hex>.parquet`)
and its commits carry it. Vacuum advances the table's *fence* to the
minimum epoch among LIVE writer leases (or past every epoch ever issued
when none are live), then collects never-referenced stages with
`epoch < fence` — safe, because a commit carrying a fenced epoch is
REFUSED at publish time. A stale zombie writer (crashed host, paused VM,
expired lease) can therefore never publish a manifest referencing files
vacuum reclaimed: it is fenced first. This replaces `_is_local()`
pid-gating — epochs travel in file names, so the contract holds on any
shared warehouse where pids mean nothing.

**Failure story.** The coordinator journals every commit to a WAL entry
(atomic rename) before publishing; `recover()` at startup prunes
published entries and ROLLS BACK unpublished ones — an unpublished entry
was never acknowledged (ack follows publish), so discarding is the
linearizable choice, while replay-forward would double-apply against the
client's own retry of the ambiguous commit. Clients resolve that
ambiguity themselves: a commit cut off mid-flight polls the manifest dir
(shared storage) for its txid before giving up — coordinator died
post-publish → success; died pre-publish → classified-retryable failure
and the recovered entry is guaranteed discarded. Coordinator-unreachable
otherwise degrades gracefully: pinned reads keep serving (snapshots
resolve against shared storage, lease registration falls back to the
local table with a warning), writes fail classified `io_transient`, and
vacuum fails conservative (it cannot see remote leases, so it must not
delete). Fault sites `catalog:commit` / `catalog:lease` /
`catalog:fence` (io/hang/crash) make every one of those paths testable
on demand.

Observability: `catalog_commit` / `catalog_lease` events (obs/trace.py),
`nds_catalog_*` metric families and a `/statusz` catalog section
(obs/metrics.py).
"""

from __future__ import annotations

import json
import os
import posixpath
import socket
import threading
import time
import uuid

from .. import faults
from ..io.fs import get_fs, put_if_absent
from ..engine.lockdebug import make_lock

#: catalog state directory inside a table root, sibling of _manifests/
CATALOG_DIR = "_catalog"
_LEASE_DIR = "leases"
_WRITER_DIR = "writers"
_WAL_DIR = "wal"
_FENCE_FILE = "fence.json"
_EPOCH_FILE = "epoch.json"

#: default writer-lease TTL seconds (engine.lake_writer_ttl_s /
#: NDS_LAKE_WRITER_TTL_S): how long a registered writer stays unfenced
#: without renewing — commits renew per attempt, so only a crashed or
#: wedged writer ever expires
DEFAULT_WRITER_TTL_S = 300.0

#: how long an ambiguous tcp commit (connection cut mid-flight) polls the
#: manifest dir for its txid before failing classified-retryable
CATALOG_POLL_ENV = "NDS_LAKE_CATALOG_POLL_S"

#: tcp client connect/read timeout seconds
CATALOG_TIMEOUT_ENV = "NDS_LAKE_CATALOG_TIMEOUT_S"


class CatalogError(Exception):
    pass


class CatalogFencedError(CatalogError):
    """This writer's epoch is below the table's fence: a vacuum decided
    it was a zombie (writer lease expired) and may have reclaimed its
    staged files. The commit was refused — republishing would reference
    deleted data. The transaction re-runs with a fresh epoch (new stage,
    new registration); table.py converts this to CommitConflictError so
    the ladder's `commit_rebase_retry` rung owns the re-run."""


class CatalogUnreachableError(CatalogError, ConnectionError):
    """The tcp coordinator did not answer. ConnectionError subclass on
    purpose: faults.classify maps it to `io_transient`, so writes walk
    the io backoff ladder while pinned reads (which never need the
    coordinator) keep serving."""


def resolve_writer_ttl(conf: dict | None = None) -> float:
    v = None
    if conf:
        v = conf.get("engine.lake_writer_ttl_s")
    if v is None:
        v = os.environ.get("NDS_LAKE_WRITER_TTL_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else (
            DEFAULT_WRITER_TTL_S
        )
    except (TypeError, ValueError):
        return DEFAULT_WRITER_TTL_S


def _catalog_spec(conf: dict | None = None):
    v = None
    if conf:
        v = conf.get("engine.lake_catalog")
    if v is None:
        v = os.environ.get("NDS_LAKE_CATALOG")
    if v is None:
        return None
    v = str(v).strip()
    return v if v and v.lower() not in ("off", "none", "0", "false") else None


#: one client per backend spec: the fs client is stateless and the tcp
#: client caches its (host, port); a dict keyed by spec keeps table
#: construction at one lookup. nds-lint: disable=mutable-module-global
_CLIENTS = {}
_CLIENTS_LOCK = make_lock("lakehouse/catalog.py:_CLIENTS_LOCK")


def resolve_catalog(conf: dict | None = None):
    """The configured catalog client (`engine.lake_catalog` /
    NDS_LAKE_CATALOG: `fs`, an `http://host:port` coordinator URL, or
    off/None — the default, the PR-10 process-concurrent behavior)."""
    spec = _catalog_spec(conf)
    if spec is None:
        return None
    with _CLIENTS_LOCK:
        client = _CLIENTS.get(spec)
        if client is None:
            if spec.startswith(("http://", "https://")):
                client = HttpCatalog(spec)
            elif spec == "fs":
                client = FsCatalog()
            else:
                raise CatalogError(
                    f"bad engine.lake_catalog value {spec!r} "
                    f"(want 'off', 'fs', or an http://host:port URL)"
                )
            _CLIENTS[spec] = client
    return client


def reset_clients():
    """Drop cached backend clients (test isolation)."""
    with _CLIENTS_LOCK:
        _CLIENTS.clear()


def _now_ms() -> int:
    return int(time.time() * 1000)


def _tracer():
    # lazy import: same pattern as lakehouse/table.py — the catalog must
    # stay importable without obs, and the thread-local binding is how
    # session-less layers find their stream's tracer
    from ..obs import trace as _obs_trace

    return _obs_trace.current()


class _TableRef:
    """Lightweight table handle for catalog ops on a bare path (the
    coordinator receives root paths over the wire; LakehouseTable itself
    duck-types this shape for the fs client)."""

    def __init__(self, path: str):
        self.path = str(path)
        self.fs, self.root = get_fs(path)
        self.name = posixpath.basename(self.root)
        self.manifest_dir = posixpath.join(self.root, "_manifests")


class RemoteLease:
    """Handle to a catalog-registered reader lease; the in-process lease
    table (lakehouse/leases.py) stores one per write-through record and
    forwards renew/release, making it the local cache of catalog state."""

    def __init__(self, catalog, ref, lease_id: str):
        self.catalog = catalog
        self.ref = ref
        self.lease_id = lease_id

    def renew(self, ttl_s: float) -> bool:
        return self.catalog.lease_renew(self.ref, self.lease_id, ttl_s)

    def release(self) -> bool:
        return self.catalog.lease_release(self.ref, self.lease_id)


# ---------------------------------------------------------------------------
# fs backend: CAS over atomic rename on the warehouse itself
# ---------------------------------------------------------------------------


class FsCatalog:
    """Catalog state as JSON files under `<root>/_catalog/`, every write
    an atomic tmp+rename. No process to run, works on any io/fs.py
    filesystem; arbitration strength is `put_if_absent`'s (atomic on
    local POSIX, best-effort-narrowed on remote stores — the tcp backend
    exists for exactly that gap)."""

    backend = "fs"

    # -- state files -----------------------------------------------------
    def _cdir(self, t, sub: str | None = None) -> str:
        d = posixpath.join(t.root, CATALOG_DIR)
        return posixpath.join(d, sub) if sub else d

    def _read_json(self, t, relpath: str):
        try:
            with t.fs.open(posixpath.join(self._cdir(t), relpath), "r") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _write_json(self, t, relpath: str, obj):
        dest = posixpath.join(self._cdir(t), relpath)
        parent = posixpath.dirname(dest)
        t.fs.makedirs(parent, exist_ok=True)
        tmp = posixpath.join(
            parent, f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        with t.fs.open(tmp, "w") as fh:
            json.dump(obj, fh)
        t.fs.mv(tmp, dest)

    def _rm(self, t, relpath: str) -> bool:
        try:
            t.fs.rm_file(posixpath.join(self._cdir(t), relpath))
            return True
        except OSError:
            return False

    def _ls(self, t, sub: str):
        try:
            return [
                posixpath.basename(f)
                for f in t.fs.ls(self._cdir(t, sub), detail=False)
            ]
        except OSError:
            return []

    # -- fence + writer epochs -------------------------------------------
    def read_fence(self, t) -> int:
        rec = self._read_json(t, _FENCE_FILE)
        try:
            return int(rec["fence"]) if rec else 0
        except (KeyError, TypeError, ValueError):
            return 0

    def _next_epoch(self, t) -> int:
        rec = self._read_json(t, _EPOCH_FILE)
        try:
            return int(rec["next"]) if rec else 1
        except (KeyError, TypeError, ValueError):
            return 1

    def writer_register(self, t, ttl_s: float) -> dict:
        """Register a TTL-bounded writer lease; returns the token
        {"id", "epoch"}. The epoch is monotone (>= fence, >= every epoch
        issued before); concurrent registrations may share an epoch,
        which only delays fencing — never breaks it (the fence is the
        MIN over live epochs)."""
        if faults.active():
            faults.maybe_fire("catalog:lease", kinds=("io", "hang", "crash"))
        epoch = max(self.read_fence(t), self._next_epoch(t))
        self._write_json(t, _EPOCH_FILE, {"next": epoch + 1})
        wid = uuid.uuid4().hex[:12]
        self._write_json(t, f"{_WRITER_DIR}/{wid}.json", {
            "epoch": epoch,
            "expires_ms": _now_ms() + int(float(ttl_s) * 1000),
            "pid": os.getpid(),
            "host": socket.gethostname(),
        })
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_lease", op="writer_register", backend=self.backend,
                outcome="ok", table=t.name, epoch=epoch,
            )
        return {"id": wid, "epoch": epoch}

    def writer_renew(self, t, token: dict, ttl_s: float) -> bool:
        rel = f"{_WRITER_DIR}/{token['id']}.json"
        rec = self._read_json(t, rel)
        if rec is None:
            return False
        rec["expires_ms"] = _now_ms() + int(float(ttl_s) * 1000)
        self._write_json(t, rel, rec)
        return True

    def _live_writer_epochs(self, t):
        now = _now_ms()
        out = []
        for base in self._ls(t, _WRITER_DIR):
            if not base.endswith(".json"):
                continue
            rec = self._read_json(t, f"{_WRITER_DIR}/{base}")
            if rec is None:
                continue
            if int(rec.get("expires_ms") or 0) <= now:
                self._rm(t, f"{_WRITER_DIR}/{base}")  # expired: prune
                continue
            try:
                out.append(int(rec["epoch"]))
            except (KeyError, TypeError, ValueError):
                continue
        return out

    def bump_fence(self, t) -> int:
        """Advance the fence to min(live writer epochs) — or past every
        epoch ever issued when none are live — and return it. Vacuum
        calls this BEFORE collecting: any stage with epoch < the returned
        fence belongs to a writer whose publish is now refused, so
        deleting it can never tear a commit. Monotone: the fence is
        never lowered."""
        if faults.active():
            faults.maybe_fire("catalog:fence", kinds=("io", "hang", "crash"))
        cur = self.read_fence(t)
        live = self._live_writer_epochs(t)
        new = max(cur, min(live) if live else self._next_epoch(t))
        if new != cur:
            self._write_json(t, _FENCE_FILE, {"fence": new})
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_lease", op="fence_bump", backend=self.backend,
                outcome="ok", table=t.name, fence=new,
                live_writers=len(live),
            )
        return new

    # -- commit -----------------------------------------------------------
    def commit(self, t, manifest: dict, epoch: int | None = None,
               txid: str | None = None,
               deadline_ms: int | None = None) -> bool:
        """Fence-checked create-exclusive publish of `manifest` as the
        next version. True = published; False = lost the version race
        (caller rebases/aborts per its transaction type); raises
        CatalogFencedError when this writer's epoch is below the fence.

        `deadline_ms` (tcp path): the client's give-up wall-clock stamp.
        A coordinator that was merely SLOW (not dead) past it must NOT
        complete the publish — the client has classified the commit
        failed-retryable and will re-run the transaction, so a late
        publish would double-apply. Checked immediately before the
        rename, i.e. after any hang spent inside this critical section;
        the residual window is inter-host clock skew, bounded by the
        client's poll budget."""
        if faults.active():
            # the mid-commit chaos site: io walks the backoff ladder,
            # hang holds the publish open for a kill, crash dies between
            # intent and publish (the coordinator's WAL-recovery food)
            faults.maybe_fire("catalog:commit", kinds=("io", "hang", "crash"))
        t0 = time.perf_counter()
        version = int(manifest["version"])
        if epoch is not None and epoch < self.read_fence(t):
            self._emit_commit(t, version, "fenced", t0)
            raise CatalogFencedError(
                f"{t.path}: writer epoch {epoch} fenced by catalog "
                f"(fence {self.read_fence(t)}); the transaction must "
                f"re-run with a fresh registration"
            )
        tmp = posixpath.join(
            t.manifest_dir, f".tmp-{os.getpid()}-{uuid.uuid4().hex}.json"
        )
        with t.fs.open(tmp, "w") as fh:
            json.dump(manifest, fh)
        # final fence re-check immediately before the rename: narrows the
        # fs backend's check-to-publish window to microseconds (the tcp
        # coordinator closes it outright by serializing fence + publish)
        if epoch is not None and epoch < self.read_fence(t):
            try:
                t.fs.rm_file(tmp)
            except OSError:
                pass
            self._emit_commit(t, version, "fenced", t0)
            raise CatalogFencedError(
                f"{t.path}: writer epoch {epoch} fenced by catalog "
                f"mid-publish; the transaction must re-run"
            )
        if deadline_ms is not None and _now_ms() > deadline_ms:
            # the client already gave up (and may already be re-running
            # the transaction): publishing now would apply it twice
            try:
                t.fs.rm_file(tmp)
            except OSError:
                pass
            self._emit_commit(t, version, "expired", t0, txid)
            return False
        dest = posixpath.join(t.manifest_dir, f"v{version:06d}.json")
        ok = put_if_absent(t.fs, tmp, dest)
        self._emit_commit(t, version, "ok" if ok else "conflict", t0, txid)
        return ok

    def _emit_commit(self, t, version, outcome, t0, txid=None):
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_commit", table=t.name, backend=self.backend,
                version=version, outcome=outcome,
                dur_ms=round((time.perf_counter() - t0) * 1000.0, 3),
                **({"txid": txid} if txid else {}),
            )

    # -- reader leases -----------------------------------------------------
    def lease_acquire(self, t, version: int, files, ttl_s: float):
        """Register a cross-host reader lease; returns a RemoteLease (or
        None when registration failed — reads keep serving, the local
        lease still protects in-process)."""
        if faults.active():
            faults.maybe_fire("catalog:lease", kinds=("io", "hang", "crash"))
        lid = uuid.uuid4().hex[:12]
        try:
            self._write_json(t, f"{_LEASE_DIR}/{lid}.json", {
                "version": int(version),
                "files": sorted(str(f) for f in files),
                "expires_ms": _now_ms() + int(float(ttl_s) * 1000),
                "pid": os.getpid(),
                "host": socket.gethostname(),
            })
        except OSError:
            return None
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_lease", op="acquire", backend=self.backend,
                outcome="ok", table=t.name, version=int(version),
            )
        return RemoteLease(self, _TableRef(t.path), lid)

    def lease_renew(self, ref, lease_id: str, ttl_s: float) -> bool:
        rel = f"{_LEASE_DIR}/{lease_id}.json"
        rec = self._read_json(ref, rel)
        if rec is None or int(rec.get("expires_ms") or 0) <= _now_ms():
            return False
        rec["expires_ms"] = _now_ms() + int(float(ttl_s) * 1000)
        try:
            self._write_json(ref, rel, rec)
        except OSError:
            return False
        return True

    def lease_release(self, ref, lease_id: str) -> bool:
        ok = self._rm(ref, f"{_LEASE_DIR}/{lease_id}.json")
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_lease", op="release", backend=self.backend,
                outcome="ok" if ok else "gone", table=ref.name,
            )
        return ok

    def _live_leases(self, t):
        now = _now_ms()
        for base in self._ls(t, _LEASE_DIR):
            if not base.endswith(".json"):
                continue
            rec = self._read_json(t, f"{_LEASE_DIR}/{base}")
            if rec is None or int(rec.get("expires_ms") or 0) <= now:
                continue
            yield rec

    def held_files(self, t) -> set:
        """Manifest-relative paths any live lease — from ANY host —
        covers; the cross-host half of vacuum's never-delete-leased
        contract."""
        out = set()
        for rec in self._live_leases(t):
            out.update(rec.get("files") or ())
        return out

    def held_versions(self, t) -> set:
        return {
            int(rec["version"]) for rec in self._live_leases(t)
            if rec.get("version") is not None
        }

    def sweep_expired(self, t) -> int:
        """Remove expired lease files (vacuum-time hygiene); live leases
        and every non-lease file are untouched."""
        now = _now_ms()
        removed = 0
        for base in self._ls(t, _LEASE_DIR):
            if not base.endswith(".json"):
                continue
            rec = self._read_json(t, f"{_LEASE_DIR}/{base}")
            if rec is not None and int(rec.get("expires_ms") or 0) <= now:
                if self._rm(t, f"{_LEASE_DIR}/{base}"):
                    removed += 1
        if removed:
            tr = _tracer()
            if tr is not None:
                tr.emit(
                    "catalog_lease", op="sweep", backend=self.backend,
                    outcome="ok", table=t.name, removed=removed,
                )
        return removed


# ---------------------------------------------------------------------------
# tcp backend: coordinator app + client
# ---------------------------------------------------------------------------


class CatalogCoordinator:
    """The single-writer commit log as a process: every /catalog/* op
    runs under ONE lock over an FsCatalog, so fence check, WAL intent,
    and manifest publish are a single critical section — no CAS window
    at all. Attached to the process-wide listener via
    `MetricsServer.attach_app` (obs/httpserv.py), exactly like serve
    mode: one port carries /metrics, /statusz AND the catalog."""

    def __init__(self, tracer=None):
        self._fs = FsCatalog()
        self._lock = make_lock("CatalogCoordinator._lock")
        self.tracer = tracer
        self._refs = {}  # path -> _TableRef  # nds-guarded-by: _lock
        self.started_ts_ms = _now_ms()
        #: kept False so obs/httpserv.py's /healthz keeps answering 200
        self.draining = False

    def _ref(self, path: str) -> _TableRef:
        # under the coordinator lock: handlers call this BEFORE their own
        # `with self._lock:` span, and two listener threads racing the
        # same path would otherwise each publish a distinct _TableRef —
        # one of them then commits against a ref nobody else can see
        with self._lock:
            ref = self._refs.get(path)
            if ref is None:
                ref = self._refs[path] = _TableRef(path)
            return ref

    def _bind(self):
        from ..obs import trace as obs_trace

        return obs_trace.bind(self.tracer) if self.tracer is not None else (
            _NullCtx()
        )

    # -- startup recovery --------------------------------------------------
    def recover(self, path: str) -> dict:
        """Replay the WAL against the manifest log after a crash:
        published entries are pruned (the commit completed and was
        acknowledged-or-pollable), unpublished entries are ROLLED BACK —
        never acknowledged, and replay-forward would double-apply against
        the client's own retry. Either way the manifest log is whole:
        publishes are atomic renames, so there is no torn manifest to
        repair, and no published (committed) version is ever dropped."""
        t = self._ref(path)
        pruned, rolled_back = 0, 0
        with self._lock, self._bind():
            for base in self._fs._ls(t, _WAL_DIR):
                if not base.endswith(".json"):
                    # a torn WAL temp (crash mid-rename): plain debris
                    self._fs._rm(t, f"{_WAL_DIR}/{base}")
                    continue
                rec = self._fs._read_json(t, f"{_WAL_DIR}/{base}")
                if rec is None:
                    self._fs._rm(t, f"{_WAL_DIR}/{base}")
                    continue
                version = int(rec.get("version") or 0)
                dest = posixpath.join(
                    t.manifest_dir, f"v{version:06d}.json"
                )
                published = t.fs.exists(dest)
                self._fs._rm(t, f"{_WAL_DIR}/{base}")
                if published:
                    pruned += 1
                else:
                    rolled_back += 1
                    tr = _tracer()
                    if tr is not None:
                        tr.emit(
                            "catalog_commit", table=t.name, backend="tcp",
                            version=version, outcome="rolled_back",
                        )
        return {
            "table": t.name, "pruned": pruned, "rolled_back": rolled_back,
        }

    def recover_warehouse(self, warehouse: str) -> list:
        """Startup recovery over every lakehouse table under a warehouse
        root (a dir owning `_manifests/` is a table)."""
        from ..io.fs import join as fs_join

        fs, root = get_fs(warehouse)
        out = []
        try:
            entries = fs.ls(root, detail=False)
        except OSError:
            return out
        for entry in sorted(entries):
            if fs.isdir(posixpath.join(entry, "_manifests")):
                out.append(self.recover(fs_join(warehouse,
                                                posixpath.basename(entry))))
        return out

    # -- HTTP seam ---------------------------------------------------------
    def handle_http(self, method, path, headers, body):
        """(status, ctype, body, extra_headers) for /catalog/* routes,
        None for anything else (the listener 404s)."""
        if method == "GET" and path == "/catalog/state":
            return self._reply(200, {"tables": sorted(self._refs)})
        if method != "POST" or not path.startswith("/catalog/"):
            return None
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError) as exc:
            return self._reply(400, {"error": f"malformed body: {exc}"})
        if not isinstance(payload, dict) or not payload.get("root"):
            return self._reply(400, {"error": "body needs 'root'"})
        try:
            if path == "/catalog/commit":
                return self._reply(200, self._do_commit(payload))
            if path == "/catalog/lease":
                return self._reply(200, self._do_lease(payload))
            if path == "/catalog/fence":
                return self._reply(200, self._do_fence(payload))
        except CatalogFencedError as exc:
            return self._reply(409, {"fenced": True, "error": str(exc)})
        except (KeyError, TypeError, ValueError) as exc:
            return self._reply(400, {"error": f"{type(exc).__name__}: {exc}"})
        return None

    @staticmethod
    def _reply(status, obj):
        return (status, "application/json", json.dumps(obj, default=str), ())

    def _do_commit(self, payload) -> dict:
        t = self._ref(str(payload["root"]))
        manifest = dict(payload["manifest"])
        epoch = payload.get("epoch")
        txid = str(payload.get("txid") or uuid.uuid4().hex)
        manifest["txid"] = txid
        version = int(manifest["version"])
        with self._lock, self._bind():
            # idempotency: a client retrying an ambiguous send must not
            # double-publish — the WAL remembers acknowledged txids until
            # recovery/pruning
            prior = self._fs._read_json(t, f"{_WAL_DIR}/{txid}.json")
            if prior is not None:
                dest = posixpath.join(
                    t.manifest_dir, f"v{int(prior['version']):06d}.json"
                )
                if t.fs.exists(dest):
                    return {"published": True,
                            "version": int(prior["version"])}
            if epoch is not None and epoch < self._fs.read_fence(t):
                raise CatalogFencedError(
                    f"{t.path}: writer epoch {epoch} fenced "
                    f"(fence {self._fs.read_fence(t)})"
                )
            # intent BEFORE publish: the replayable log the chaos test
            # kills us over — a crash between these two steps leaves a
            # WAL entry recovery rolls back (never acknowledged)
            self._fs._write_json(t, f"{_WAL_DIR}/{txid}.json", {
                "version": version, "txid": txid,
            })
            deadline = payload.get("deadline_ms")
            published = self._fs.commit(
                t, manifest, epoch=epoch, txid=txid,
                deadline_ms=int(deadline) if deadline else None,
            )
            if not published:
                # lost to a non-coordinated writer (mixed-mode warehouse):
                # drop the intent, the client rebases
                self._fs._rm(t, f"{_WAL_DIR}/{txid}.json")
            else:
                self._prune_wal(t, version)
        return {"published": published, "version": version}

    #: published WAL entries kept for idempotent-retry detection before
    #: pruning kicks in (a retry older than this many commits is settled)
    WAL_KEEP = 128

    def _prune_wal(self, t, head_version: int):
        """Bound the journal: entries `WAL_KEEP` commits behind the head
        are settled (their clients long since answered) and removed.
        Caller holds the lock."""
        entries = self._fs._ls(t, _WAL_DIR)
        if len(entries) <= self.WAL_KEEP:
            return
        for base in entries:
            if not base.endswith(".json"):
                continue
            rec = self._fs._read_json(t, f"{_WAL_DIR}/{base}")
            if rec is None or (
                int(rec.get("version") or 0) <= head_version - self.WAL_KEEP
            ):
                self._fs._rm(t, f"{_WAL_DIR}/{base}")

    def _do_lease(self, payload) -> dict:
        t = self._ref(str(payload["root"]))
        op = str(payload.get("op") or "")
        with self._lock, self._bind():
            if op == "acquire":
                lease = self._fs.lease_acquire(
                    t, int(payload["version"]), payload.get("files") or (),
                    float(payload.get("ttl_s") or 0.0),
                )
                return {"lease_id": lease.lease_id if lease else None}
            if op == "renew":
                return {"ok": self._fs.lease_renew(
                    t, str(payload["lease_id"]),
                    float(payload.get("ttl_s") or 0.0),
                )}
            if op == "release":
                return {"ok": self._fs.lease_release(
                    t, str(payload["lease_id"])
                )}
            if op == "held":
                return {
                    "files": sorted(self._fs.held_files(t)),
                    "versions": sorted(self._fs.held_versions(t)),
                }
            if op == "sweep":
                return {"removed": self._fs.sweep_expired(t)}
        raise ValueError(f"unknown lease op {op!r}")

    def _do_fence(self, payload) -> dict:
        t = self._ref(str(payload["root"]))
        op = str(payload.get("op") or "")
        with self._lock, self._bind():
            # ttl 0.0 is a meaningful value (release-now, from
            # _release_writer) — only an ABSENT ttl takes the default
            ttl = payload.get("ttl_s")
            ttl = DEFAULT_WRITER_TTL_S if ttl is None else float(ttl)
            if op == "writer_register":
                return self._fs.writer_register(t, ttl)
            if op == "writer_renew":
                return {"ok": self._fs.writer_renew(
                    t, {"id": str(payload["id"])}, ttl,
                )}
            if op == "read":
                return {"fence": self._fs.read_fence(t)}
            if op == "bump":
                return {"fence": self._fs.bump_fence(t)}
        raise ValueError(f"unknown fence op {op!r}")


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class HttpCatalog:
    """Client for a CatalogCoordinator. Same API shape as FsCatalog; all
    state lives with the coordinator (and, through it, the warehouse),
    so this object is just an address."""

    backend = "tcp"

    def __init__(self, url: str):
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        if not parts.hostname or not parts.port:
            raise CatalogError(
                f"bad catalog URL {url!r} (want http://host:port)"
            )
        self.url = url
        self.host = parts.hostname
        self.port = int(parts.port)
        try:
            self.timeout_s = float(
                os.environ.get(CATALOG_TIMEOUT_ENV, "5.0")
            )
        except ValueError:
            self.timeout_s = 5.0
        self._warned_lease = False

    # -- transport ---------------------------------------------------------
    def _post(self, route: str, payload: dict,
              timeout_s: float | None = None) -> dict:
        import http.client

        if faults.active():
            # fleet chaos site: an injected io fault here makes the
            # coordinator unreachable WITHOUT killing its process — the
            # same CatalogUnreachableError surface a SIGKILL'd
            # coordinator produces (degraded-mode drills in-process)
            try:
                faults.maybe_fire("catalog:unreachable", kinds=("io", "hang"))
            except faults.FaultError as exc:
                raise CatalogUnreachableError(
                    f"catalog unreachable at {self.url} (injected: {exc})"
                ) from exc
        body = json.dumps(payload).encode("utf-8")
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout_s is None else timeout_s,
        )
        try:
            conn.request(
                "POST", route, body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            data = resp.read()
        except (OSError, http.client.HTTPException) as exc:
            raise CatalogUnreachableError(
                f"catalog unreachable at {self.url} "
                f"({type(exc).__name__}: {exc})"
            ) from exc
        finally:
            conn.close()
        try:
            obj = json.loads(data.decode("utf-8")) if data else {}
        except (ValueError, UnicodeDecodeError):
            obj = {}
        if resp.status == 409 and obj.get("fenced"):
            raise CatalogFencedError(
                obj.get("error") or "writer fenced by catalog"
            )
        if resp.status >= 400:
            raise CatalogError(
                f"catalog {route} failed ({resp.status}): "
                f"{obj.get('error') or data[:200]!r}"
            )
        return obj

    # -- API ---------------------------------------------------------------
    def writer_register(self, t, ttl_s: float) -> dict:
        if faults.active():
            faults.maybe_fire("catalog:lease", kinds=("io", "hang"))
        return self._post(
            "/catalog/fence",
            {"op": "writer_register", "root": t.path, "ttl_s": ttl_s},
        )

    def writer_renew(self, t, token: dict, ttl_s: float) -> bool:
        try:
            return bool(self._post("/catalog/fence", {
                "op": "writer_renew", "root": t.path, "id": token["id"],
                "ttl_s": ttl_s,
            }).get("ok"))
        except CatalogUnreachableError:
            return False  # renewal is best-effort; commit re-arbitrates

    def read_fence(self, t) -> int:
        return int(self._post(
            "/catalog/fence", {"op": "read", "root": t.path}
        ).get("fence") or 0)

    def bump_fence(self, t) -> int:
        if faults.active():
            faults.maybe_fire("catalog:fence", kinds=("io", "hang"))
        return int(self._post(
            "/catalog/fence", {"op": "bump", "root": t.path}
        ).get("fence") or 0)

    def commit(self, t, manifest: dict, epoch: int | None = None,
               txid: str | None = None) -> bool:
        if faults.active():
            faults.maybe_fire("catalog:commit", kinds=("io", "hang"))
        t0 = time.perf_counter()
        txid = txid or uuid.uuid4().hex
        version = int(manifest["version"])
        try:
            # the publish deadline: how long this client will wait (post
            # timeout + ambiguity poll) before declaring the commit
            # failed-retryable. A coordinator that is slow past it must
            # refuse the late publish — otherwise this client's re-run
            # would apply the transaction twice.
            deadline_ms = _now_ms() + int(
                (self.timeout_s + self._poll_budget()) * 1000
            )
            resp = self._post("/catalog/commit", {
                "root": t.path, "manifest": manifest, "epoch": epoch,
                "txid": txid, "deadline_ms": deadline_ms,
            })
        except CatalogFencedError:
            self._emit_commit(t, version, "fenced", t0, txid)
            raise
        except CatalogUnreachableError:
            # ambiguous outcome: the coordinator may have published just
            # before dying. The manifest log is shared storage — poll it
            # for OUR txid before declaring the write failed-retryable
            # (recovery guarantees an unpublished intent is rolled back,
            # so a clean retry can never double-apply).
            outcome = self._poll_published(t, version, txid)
            if outcome is not None:
                self._emit_commit(
                    t, version, "ok" if outcome else "conflict", t0, txid
                )
                return outcome
            self._emit_commit(t, version, "unreachable", t0, txid)
            raise
        published = bool(resp.get("published"))
        self._emit_commit(
            t, version, "ok" if published else "conflict", t0, txid
        )
        return published

    @staticmethod
    def _poll_budget() -> float:
        try:
            return float(os.environ.get(CATALOG_POLL_ENV, "2.0"))
        except ValueError:
            return 2.0

    def _poll_published(self, t, version: int, txid: str):
        """True = our txid owns the version; False = someone else does
        (lost race); None = version still unpublished after the window —
        and guaranteed to STAY unpublished: the coordinator refuses
        publishes past the deadline this client sent, and restart
        recovery rolls the WAL intent back (residual window: inter-host
        clock skew only)."""
        budget = self._poll_budget()
        deadline = time.perf_counter() + budget
        dest = posixpath.join(t.manifest_dir, f"v{version:06d}.json")
        while True:
            try:
                with t.fs.open(dest, "r") as fh:
                    rec = json.load(fh)
                return rec.get("txid") == txid
            except (OSError, ValueError):
                pass
            if time.perf_counter() >= deadline:
                return None
            time.sleep(min(0.05, budget))

    def _emit_commit(self, t, version, outcome, t0, txid):
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_commit", table=t.name, backend=self.backend,
                version=version, outcome=outcome, txid=txid,
                dur_ms=round((time.perf_counter() - t0) * 1000.0, 3),
            )

    # -- leases ------------------------------------------------------------
    def lease_acquire(self, t, version: int, files, ttl_s: float):
        if faults.active():
            faults.maybe_fire("catalog:lease", kinds=("io", "hang"))
        try:
            lid = self._post("/catalog/lease", {
                "op": "acquire", "root": t.path, "version": int(version),
                "files": sorted(str(f) for f in files), "ttl_s": ttl_s,
            }).get("lease_id")
        except CatalogUnreachableError:
            # graceful read-side degradation: the pin still holds locally
            # (in-process lease table); only cross-host visibility is
            # lost until the coordinator returns
            if not self._warned_lease:
                self._warned_lease = True
                print(
                    f"catalog: coordinator {self.url} unreachable; reader "
                    f"leases degrade to process-local until it returns"
                )
            return None
        if not lid:
            return None
        tr = _tracer()
        if tr is not None:
            tr.emit(
                "catalog_lease", op="acquire", backend=self.backend,
                outcome="ok", table=t.name, version=int(version),
            )
        return RemoteLease(self, _TableRef(t.path), str(lid))

    def lease_renew(self, ref, lease_id: str, ttl_s: float) -> bool:
        try:
            # renewal runs on the memwatch heartbeat thread: cap the
            # blocking window well below the general timeout so a slow
            # coordinator cannot stall the OOM-watermark sampling
            return bool(self._post("/catalog/lease", {
                "op": "renew", "root": ref.path, "lease_id": lease_id,
                "ttl_s": ttl_s,
            }, timeout_s=min(self.timeout_s, 1.0)).get("ok"))
        except CatalogUnreachableError:
            return False

    def lease_release(self, ref, lease_id: str) -> bool:
        try:
            return bool(self._post("/catalog/lease", {
                "op": "release", "root": ref.path, "lease_id": lease_id,
            }).get("ok"))
        except CatalogUnreachableError:
            return False  # TTL expiry is the backstop

    def held_files(self, t) -> set:
        # NO unreachable fallback here on purpose: vacuum consults this,
        # and a vacuum that cannot see remote leases must fail (the
        # classified-retryable error), not delete blind
        return set(self._post(
            "/catalog/lease", {"op": "held", "root": t.path}
        ).get("files") or ())

    def held_versions(self, t) -> set:
        return {int(v) for v in self._post(
            "/catalog/lease", {"op": "held", "root": t.path}
        ).get("versions") or ()}

    def sweep_expired(self, t) -> int:
        try:
            return int(self._post(
                "/catalog/lease", {"op": "sweep", "root": t.path}
            ).get("removed") or 0)
        except CatalogUnreachableError:
            return 0
