"""DML execution over lakehouse tables: INSERT / DELETE / CTAS / CALL.

The reference's Data Maintenance phase issues these against Iceberg/Delta
through Spark SQL (reference: nds/nds_maintenance.py:188-202 run_dm_query,
nds/data_maintenance/LF_SS.sql:31-68, DF_SS.sql:30-33, nds/nds_rollback.py:46-51).
Here they execute through the engine and commit snapshots to the manifest
log. DELETE keeps rows whose predicate is not TRUE (SQL three-valued
semantics: a NULL predicate row survives), implemented as a copy-on-write
rewrite of the surviving rows.
"""

from __future__ import annotations

import os

from ..engine import expr as E
from ..engine.sql import ast as A
from .table import LakehouseError, LakehouseTable


class DmlResult:
    """Mirrors the tiny surface of engine.session.Result the harness uses."""

    def __init__(self, rows_affected: int, version: int | None = None):
        self.rows_affected = rows_affected
        self.version = version

    def collect(self):
        import pyarrow as pa

        return pa.table({"rows_affected": [self.rows_affected]})

    def num_rows(self):
        return 1


def _lake_table(session, name: str) -> LakehouseTable:
    entry = session.catalog.entries.get(name.lower())
    if entry is None or entry.path is None:
        raise LakehouseError(
            f"{name!r} is not a lakehouse table registered on this session"
        )
    # thread the session conf so the table's OCC commit loop and vacuum
    # retention read the engine.lake_* knobs
    return LakehouseTable(entry.path, conf=getattr(session, "conf", None))


def run_dml(session, stmt):
    if isinstance(stmt, A.InsertStmt):
        return _run_insert(session, stmt)
    if isinstance(stmt, A.DeleteStmt):
        return _run_delete(session, stmt)
    if isinstance(stmt, A.CreateTableStmt):
        return _run_ctas(session, stmt)
    if isinstance(stmt, A.CallStmt):
        return _run_call(session, stmt)
    raise TypeError(f"unsupported DML statement {type(stmt).__name__}")


def _cast_to_schema(rows, target):
    """Positional insert-cast with Spark-like leniency: decimal rescale and
    float narrowing truncate instead of erroring."""
    import pyarrow as pa
    import pyarrow.compute as pc

    cols = []
    for i, field in enumerate(target):
        col = rows.column(i)
        if col.type != field.type:
            col = pc.cast(
                col,
                options=pc.CastOptions(
                    target_type=field.type,
                    allow_decimal_truncate=True,
                    allow_float_truncate=True,
                ),
            )
        cols.append(col)
    return pa.table(cols, schema=target)


def _run_insert(session, stmt: A.InsertStmt):
    table = _lake_table(session, stmt.table)
    rows = session.run_stmt(stmt.query).collect()
    target = table.schema()
    if target is not None:
        rows = _cast_to_schema(rows, target)
    version = table.append(rows, operation="insert")
    session.catalog.invalidate(stmt.table.lower())
    return DmlResult(rows.num_rows, version)


def _run_delete(session, stmt: A.DeleteStmt):
    table = _lake_table(session, stmt.table)
    # snapshot-isolated transaction: every read of this DELETE (the
    # row count, the survivor scan — arrow or engine path) resolves ONE
    # manifest version, so a commit racing the statement can't make the
    # "before" count and the scanned rows disagree. The final replace()
    # then aborts with CommitConflictError if the head moved (overwrite
    # transactions never rebase — lakehouse/table.py conflict matrix).
    # Pinning the catalog entry registers the READER LEASE for this
    # snapshot's files up front, so a concurrent vacuum can't delete
    # them mid-scan on ANY of the paths below.
    snap = table.snapshot()
    name = stmt.table.lower()
    session.catalog.pin_lakehouse(name, version=snap.version)
    before = snap.dataset().count_rows()
    if stmt.where is None:
        # DELETE FROM t -> truncate
        target = snap.schema()
        if target is None:
            raise LakehouseError(f"{stmt.table}: table has no schema")
        version = table.replace(target.empty_table(), operation="delete")
        session.catalog.invalidate(stmt.table.lower())
        return DmlResult(before, version)

    arrow_pred = _to_arrow_predicate(session, stmt.where)
    if arrow_pred is not None:
        # streaming copy-on-write: scan file-by-file with predicate pushdown
        # and stage survivor batches directly — the survivor set never
        # materializes on host (at SF3000 a ranged fact DELETE would
        # otherwise round-trip billions of rows through one host's memory)
        keep = arrow_pred.is_null() | ~arrow_pred  # NULL predicate survives
        scanner = snap.dataset().scanner(filter=keep, batch_size=1 << 20)
        deleted = 0
        version = None

        def batches():
            nonlocal deleted
            survived = 0
            for b in scanner.to_batches():
                survived += b.num_rows
                yield b
            deleted = before - survived

        version = table.replace(batches(), operation="delete")
        session.catalog.invalidate(stmt.table.lower())
        return DmlResult(deleted, version)

    # engine fallback for predicates the Arrow translator can't express:
    # survivors are rows where the predicate is FALSE or NULL. The pin
    # (registered above) is HELD so the nested SELECT (and any scalar
    # subquery over the target) reads the same version the row count
    # came from.
    keep = E.UnaryOp("not", E.Func("coalesce", (stmt.where, E.Lit(False))))
    query = A.SelectStmt(
        select_items=[("*", None)],
        from_items=[A.TableRef(stmt.table)],
        where=keep,
    )
    with session.catalog.hold_pins([name]):
        survivors = session.run_stmt(query).collect()
    target = snap.schema()
    if target is not None:
        survivors = _cast_to_schema(survivors, target)
    version = table.replace(survivors, operation="delete")
    session.catalog.invalidate(stmt.table.lower())
    return DmlResult(before - survivors.num_rows, version)


def _to_arrow_predicate(session, e):
    """Translate a DELETE predicate into a pyarrow dataset expression,
    evaluating scalar subqueries through the engine first (DF_* predicates
    are ranged comparisons against date-keyed scalar subqueries; reference:
    nds/data_maintenance/DF_SS.sql:30-33). Returns None when the predicate
    uses something the translator doesn't cover (caller falls back to the
    engine path)."""
    import datetime

    import pyarrow.dataset as pads

    from ..engine import expr as EX

    class _Unsupported(Exception):
        pass

    def scalar_value(sub):
        res = session.run_stmt(sub.query)
        t = res.collect()
        if t.num_rows == 0:
            raise _Unsupported()  # NULL scalar: engine path handles 3VL
        v = t.column(0)[0].as_py()
        if v is None:
            raise _Unsupported()
        return v

    def lit_value(x):
        if x.dtype is not None and x.dtype.kind == "date" and isinstance(
            x.value, str
        ):
            y, m, d = x.value.split("-")
            return datetime.date(int(y), int(m), int(d))
        return x.value

    def rec(x):
        if isinstance(x, EX.Lit):
            return lit_value(x)
        if isinstance(x, EX.Col):
            return pads.field(x.name)
        if isinstance(x, EX.SubqueryExpr):
            if x.kind != "scalar":
                raise _Unsupported()
            return scalar_value(x)
        if isinstance(x, EX.Cast):
            # only the date-of-string-literal form translates exactly; any
            # other cast would silently change comparison semantics
            if x.target.kind == "date":
                inner = x.operand
                if isinstance(inner, EX.Lit) and isinstance(inner.value, str):
                    y, m, d = inner.value.split("-")
                    return datetime.date(int(y), int(m), int(d))
            raise _Unsupported()
        if isinstance(x, EX.Between):
            op = as_expr(rec(x.operand))
            lo, hi = rec(x.low), rec(x.high)
            out = (op >= lo) & (op <= hi)
            return ~out if x.negated else out
        if isinstance(x, EX.InList):
            op = as_expr(rec(x.operand))
            vals = [rec(v) for v in x.values]
            if any(v is None for v in vals):
                # NULL in the IN list: Arrow isin is 2-valued, SQL is 3VL
                raise _Unsupported()
            out = op.isin(vals)
            return ~out if x.negated else out
        if isinstance(x, EX.UnaryOp):
            if x.op == "not":
                return ~as_expr(rec(x.operand))
            if x.op == "isnull":
                return as_expr(rec(x.operand)).is_null()
            if x.op == "isnotnull":
                return as_expr(rec(x.operand)).is_valid()
            raise _Unsupported()
        if isinstance(x, EX.BinOp):
            a, b = rec(x.left), rec(x.right)
            if x.op in ("and", "or"):
                a, b = as_expr(a), as_expr(b)
                return a & b if x.op == "and" else a | b
            if not isinstance(a, pads.Expression) and not isinstance(
                b, pads.Expression
            ):
                # literal-vs-literal comparison folds to a Python bool,
                # which cannot participate in an Arrow filter
                raise _Unsupported()
            ops = {
                "=": lambda: a == b, "<>": lambda: a != b,
                "<": lambda: a < b, "<=": lambda: a <= b,
                ">": lambda: a > b, ">=": lambda: a >= b,
            }
            if x.op not in ops:
                raise _Unsupported()
            return ops[x.op]()
        raise _Unsupported()

    def as_expr(v):
        if not isinstance(v, pads.Expression):
            raise _Unsupported()
        return v

    try:
        return as_expr(rec(e))
    except _Unsupported:
        return None


def _run_ctas(session, stmt: A.CreateTableStmt):
    rows = session.run_stmt(stmt.query).collect()
    location = stmt.location
    if location is None:
        root = session.conf.get("lakehouse.warehouse")
        if root is None:
            raise LakehouseError(
                "CREATE TABLE needs a LOCATION or session conf "
                "'lakehouse.warehouse'"
            )
        location = os.path.join(root, stmt.name.lower())
    LakehouseTable.create(location, rows)
    session.register_lakehouse(stmt.name, location)
    return DmlResult(rows.num_rows, 1)


def _run_call(session, stmt: A.CallStmt):
    proc = stmt.procedure.rsplit(".", 1)[-1].lower()
    if proc != "rollback_to_timestamp":
        raise LakehouseError(f"unknown procedure {stmt.procedure}")
    def unwrap(a):
        return a.value if isinstance(a, E.Lit) else a

    table_name, ts = unwrap(stmt.args[0]), unwrap(stmt.args[1])
    table = _lake_table(session, str(table_name))
    ts_ms = _to_ts_ms(ts)
    version = table.rollback_to_timestamp(ts_ms)
    session.catalog.invalidate(str(table_name).lower())
    return DmlResult(0, version)


def _to_ts_ms(ts) -> int:
    if isinstance(ts, str):
        try:
            v = float(ts)  # CLI args arrive as strings
        except ValueError:
            v = None
        # only plausible epoch magnitudes: seconds in [1e9, 1e10) (~2001..
        # 2286) or milliseconds in [1e12, 1e13) (same era). Anything else —
        # dash-less dates like '20240101' (8 digits), '202401011200'
        # (12 digits, ~2e11) or '20240101120000' (14 digits, ~2e13) — must
        # fall through to the date parser and error loudly, not be taken
        # as an epoch in 1970, 8383 or 2611
        if v is not None and (10**9 <= v < 10**10 or 10**12 <= v < 10**13):
            ts = v
    if isinstance(ts, (int, float)):
        # numeric: epoch seconds (fractional ok) or ms if large
        return int(ts if ts >= 10**12 else ts * 1000)
    from datetime import datetime

    s = str(ts).strip()
    for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            # naive timestamps are local time, like the snapshot log prints
            dt = datetime.strptime(s, fmt)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise LakehouseError(f"cannot parse timestamp {ts!r}")
