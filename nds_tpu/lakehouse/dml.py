"""DML execution over lakehouse tables: INSERT / DELETE / CTAS / CALL.

The reference's Data Maintenance phase issues these against Iceberg/Delta
through Spark SQL (reference: nds/nds_maintenance.py:188-202 run_dm_query,
nds/data_maintenance/LF_SS.sql:31-68, DF_SS.sql:30-33, nds/nds_rollback.py:46-51).
Here they execute through the engine and commit snapshots to the manifest
log. DELETE keeps rows whose predicate is not TRUE (SQL three-valued
semantics: a NULL predicate row survives), implemented as a copy-on-write
rewrite of the surviving rows.
"""

from __future__ import annotations

import os

from ..engine import expr as E
from ..engine.sql import ast as A
from .table import LakehouseError, LakehouseTable


class DmlResult:
    """Mirrors the tiny surface of engine.session.Result the harness uses."""

    def __init__(self, rows_affected: int, version: int | None = None):
        self.rows_affected = rows_affected
        self.version = version

    def collect(self):
        import pyarrow as pa

        return pa.table({"rows_affected": [self.rows_affected]})

    def num_rows(self):
        return 1


def _lake_table(session, name: str) -> LakehouseTable:
    entry = session.catalog.entries.get(name.lower())
    if entry is None or entry.path is None:
        raise LakehouseError(
            f"{name!r} is not a lakehouse table registered on this session"
        )
    return LakehouseTable(entry.path)


def run_dml(session, stmt):
    if isinstance(stmt, A.InsertStmt):
        return _run_insert(session, stmt)
    if isinstance(stmt, A.DeleteStmt):
        return _run_delete(session, stmt)
    if isinstance(stmt, A.CreateTableStmt):
        return _run_ctas(session, stmt)
    if isinstance(stmt, A.CallStmt):
        return _run_call(session, stmt)
    raise TypeError(f"unsupported DML statement {type(stmt).__name__}")


def _cast_to_schema(rows, target):
    """Positional insert-cast with Spark-like leniency: decimal rescale and
    float narrowing truncate instead of erroring."""
    import pyarrow as pa
    import pyarrow.compute as pc

    cols = []
    for i, field in enumerate(target):
        col = rows.column(i)
        if col.type != field.type:
            col = pc.cast(
                col,
                options=pc.CastOptions(
                    target_type=field.type,
                    allow_decimal_truncate=True,
                    allow_float_truncate=True,
                ),
            )
        cols.append(col)
    return pa.table(cols, schema=target)


def _run_insert(session, stmt: A.InsertStmt):
    table = _lake_table(session, stmt.table)
    rows = session.run_stmt(stmt.query).collect()
    target = table.schema()
    if target is not None:
        rows = _cast_to_schema(rows, target)
    version = table.append(rows, operation="insert")
    session.catalog.invalidate(stmt.table.lower())
    return DmlResult(rows.num_rows, version)


def _run_delete(session, stmt: A.DeleteStmt):
    table = _lake_table(session, stmt.table)
    before = table.dataset().count_rows()
    if stmt.where is None:
        keep = None  # DELETE FROM t -> truncate
    else:
        # survivors: rows where the predicate is FALSE or NULL
        keep = E.UnaryOp(
            "not", E.Func("coalesce", (stmt.where, E.Lit(False)))
        )
    query = A.SelectStmt(
        select_items=[("*", None)],
        from_items=[A.TableRef(stmt.table)],
        where=keep,
    )
    if keep is None:
        target = table.schema()
        if target is None:
            raise LakehouseError(f"{stmt.table}: table has no schema")
        survivors = target.empty_table()
    else:
        survivors = session.run_stmt(query).collect()
        target = table.schema()
        if target is not None:
            survivors = _cast_to_schema(survivors, target)
    version = table.replace(survivors, operation="delete")
    session.catalog.invalidate(stmt.table.lower())
    return DmlResult(before - survivors.num_rows, version)


def _run_ctas(session, stmt: A.CreateTableStmt):
    rows = session.run_stmt(stmt.query).collect()
    location = stmt.location
    if location is None:
        root = session.conf.get("lakehouse.warehouse")
        if root is None:
            raise LakehouseError(
                "CREATE TABLE needs a LOCATION or session conf "
                "'lakehouse.warehouse'"
            )
        location = os.path.join(root, stmt.name.lower())
    LakehouseTable.create(location, rows)
    session.register_lakehouse(stmt.name, location)
    return DmlResult(rows.num_rows, 1)


def _run_call(session, stmt: A.CallStmt):
    proc = stmt.procedure.rsplit(".", 1)[-1].lower()
    if proc != "rollback_to_timestamp":
        raise LakehouseError(f"unknown procedure {stmt.procedure}")
    def unwrap(a):
        return a.value if isinstance(a, E.Lit) else a

    table_name, ts = unwrap(stmt.args[0]), unwrap(stmt.args[1])
    table = _lake_table(session, str(table_name))
    ts_ms = _to_ts_ms(ts)
    version = table.rollback_to_timestamp(ts_ms)
    session.catalog.invalidate(str(table_name).lower())
    return DmlResult(0, version)


def _to_ts_ms(ts) -> int:
    if isinstance(ts, (int, float)):
        # numeric: epoch seconds (fractional ok) or ms if large
        return int(ts if ts > 10**12 else ts * 1000)
    from datetime import datetime

    s = str(ts).strip()
    for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            # naive timestamps are local time, like the snapshot log prints
            dt = datetime.strptime(s, fmt)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise LakehouseError(f"cannot parse timestamp {ts!r}")
