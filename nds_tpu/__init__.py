"""nds-tpu: a TPU-native decision-support benchmark framework.

Re-creation of the NDS v2.0 benchmark harness (reference:
willb/spark-rapids-benchmarks) with the GPU (RAPIDS/cuDF) execution path
replaced by a TPU columnar execution engine built on JAX/XLA/Pallas.

Layout:
  schema / dtypes     - typed TPC-DS schema registry (Arrow + device mappings)
  datagen             - native C++ data generator + drivers, query-stream gen
  engine              - SQL frontend -> logical plan -> TPU columnar execution
  ops                 - kernel library (XLA ops + Pallas kernels)
  parallel            - device mesh, sharded execution, distributed exchange
  io                  - CSV/Parquet/columnar IO (Arrow-based)
  lakehouse           - snapshot-based ACID table layer (delta/iceberg parity)
  cli                 - one CLI per benchmark phase (gen_data, transcode,
                        power, maintenance, validate, rollback, bench, ...)
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("NDS_PLATFORM"):
    # Select the jax backend before anything initializes it. The env image
    # pre-registers the TPU plugin at interpreter start, so JAX_PLATFORMS in
    # the environment is consumed too early — only jax.config works here.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["NDS_PLATFORM"])
