"""Logical type system for the TPU columnar engine.

One small set of logical types spans the whole framework: the schema registry,
the CSV/Parquet IO layer (Arrow types), and the device representation (JAX
dtypes in TPU HBM). Parity target: the type surface used by the reference
schema registry (reference: nds/nds_schema.py:43-47 decimal/double switch,
:36-41 Char/Varchar semantics).

Device mapping is TPU-first:
  - int32 / int64            -> native jnp ints
  - decimal(p,s)             -> scaled int64 (value * 10^s); float64 in --float mode
  - date                     -> int32 epoch days
  - char(n)/varchar(n)/string-> int32 dictionary codes (per-column host dictionary)
Strings never travel to the device as bytes: they are dictionary-encoded on the
host and only their codes participate in TPU kernels, which keeps every hot op
a dense integer/float op that XLA can tile onto the VPU/MXU.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from functools import lru_cache

import numpy as np
import pyarrow as pa


@dataclass(frozen=True)
class DType:
    """A logical column type.

    kind: one of int32, int64, float64, decimal, date, char, varchar, string
    a, b: decimal precision/scale, or char/varchar length in `a`.
    """

    kind: str
    a: int = 0
    b: int = 0

    # ---- classification -------------------------------------------------
    @property
    def is_bool(self) -> bool:
        return self.kind == "bool"

    @property
    def is_string(self) -> bool:
        return self.kind in ("char", "varchar", "string")

    @property
    def is_decimal(self) -> bool:
        return self.kind == "decimal"

    @property
    def is_integer(self) -> bool:
        return self.kind in ("int32", "int64")

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.kind in ("float64", "decimal")

    @property
    def precision(self) -> int:
        return self.a

    @property
    def scale(self) -> int:
        return self.b

    @property
    def length(self) -> int:
        return self.a

    # ---- conversions ----------------------------------------------------
    def to_arrow(self, use_decimal: bool = True) -> pa.DataType:
        """Arrow physical type used for host-side IO (CSV scan, Parquet)."""
        k = self.kind
        if k == "int32":
            return pa.int32()
        if k == "int64":
            return pa.int64()
        if k == "float64":
            return pa.float64()
        if k == "bool":
            return pa.bool_()
        if k == "decimal":
            return pa.decimal128(self.a, self.b) if use_decimal else pa.float64()
        if k == "date":
            return pa.date32()
        if self.is_string:
            return pa.string()
        raise ValueError(f"no arrow mapping for {self}")

    def device_np_dtype(self, use_decimal: bool = True):
        """numpy dtype of the dense device buffer for this logical type."""
        k = self.kind
        if k == "int32":
            return np.int32
        if k == "int64":
            return np.int64
        if k == "float64":
            return np.float64
        if k == "bool":
            return np.bool_
        if k == "decimal":
            return np.int64 if use_decimal else np.float64
        if k == "date":
            return np.int32
        if self.is_string:
            return np.int32  # dictionary codes
        raise ValueError(f"no device mapping for {self}")

    def __str__(self) -> str:
        if self.kind == "decimal":
            return f"decimal({self.a},{self.b})"
        if self.kind in ("char", "varchar"):
            return f"{self.kind}({self.a})"
        return self.kind


_PAREN = re.compile(r"^(\w+)\((\d+)(?:,(\d+))?\)$")


@lru_cache(maxsize=None)
def parse_dtype(s: str) -> DType:
    s = s.strip().lower()
    m = _PAREN.match(s)
    if m:
        kind, a, b = m.group(1), int(m.group(2)), int(m.group(3) or 0)
        if kind not in ("decimal", "char", "varchar"):
            raise ValueError(f"bad parameterized type: {s}")
        return DType(kind, a, b)
    if s in ("int32", "int64", "float64", "date", "string", "bool"):
        return DType(s)
    raise ValueError(f"unknown dtype: {s}")


# Convenience singletons used across the engine.
INT32 = DType("int32")
INT64 = DType("int64")
FLOAT64 = DType("float64")
DATE = DType("date")
STRING = DType("string")
BOOL = DType("bool")


def common_numeric(a: DType, b: DType) -> DType:
    """Result type of arithmetic between two numeric logical types.

    Mirrors Spark's simple promotion lattice closely enough for TPC-DS:
    decimal beats float? No — Spark promotes decimal+double to double; and
    decimal op decimal widens precision/scale. We keep decimals closed under
    +,-,* with widened scale handled by the expression layer.
    """
    if a.kind == "float64" or b.kind == "float64":
        return FLOAT64
    if a.is_decimal and b.is_decimal:
        return DType("decimal", min(38, max(a.a, b.a) + 1), max(a.b, b.b))
    if a.is_decimal:
        return a
    if b.is_decimal:
        return b
    if a.kind == "int64" or b.kind == "int64":
        return INT64
    return INT32
