"""Always-on flight recorder: a bounded in-memory ring of recent events
plus self-contained failure bundles.

The reference harness gets post-mortem forensics free from Spark's event
log + history server — but only when the event log is configured, and a
crashed driver still scatters its evidence. This engine's equivalent is
deliberately ALWAYS on: every `Tracer.emit` (file-backed, sink-only, or
the new ring-only default) also appends the event to one process-wide
bounded ring (`collections.deque(maxlen=...)` — append is GIL-atomic, so
emitters never block on a flush), and on a failure the ring is flushed
as a `failure-bundle-<trace_id>.json` that carries everything a human
needs to diagnose the incident WITHOUT the trace dir that may never have
been configured:

    ring events (last N, schema-valid — they came from real emitters),
    the failing statement's plan explain + budget verdict,
    the degradation-ladder history,
    host-RSS / per-device HBM high-water,
    a redacted engine-conf snapshot.

Flush triggers (report.py + faults.py): watchdog fire, terminal query
failure (ladder exhaustion), an injected crash rule (evidence lands
before the process dies), and on demand via the `/debug/flight` endpoint
(obs/httpserv.py — the one process-wide listener).

Knobs: `engine.flight_recorder` / NDS_FLIGHT_RECORDER ("off"/"0"
disables the ring AND restores the historical tracer-is-None zero-cost
default), `engine.flight_ring_events` / NDS_FLIGHT_RING_EVENTS (ring
capacity, default 512), `engine.flight_dir` / NDS_FLIGHT_DIR (bundle
destination; defaults to the trace dir when one is configured, else
`<tempdir>/nds-flight`).

Overhead contract: the ring-only default costs one dict build + one
deque append per event; ci/tier1-check's diagnosis gate measures the
per-event cost against a real SF0.01 stream's event volume and fails
when the modeled share of wall exceeds 2%.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque

from .. import __version__
from ..engine.lockdebug import make_lock

#: default ring capacity (events); enough to hold a failing query's last
#: op spans + heartbeats from every live thread without unbounded memory
DEFAULT_RING_EVENTS = 512

#: bundle filename prefix (the reader/profiler discover bundles by it)
BUNDLE_PREFIX = "failure-bundle-"

#: top-level keys every bundle carries (validate_bundle's contract);
#: evidence sections may be null when the incident left no such evidence,
#: but the KEY must be present so a consumer can tell "no ladder walked"
#: from "truncated bundle"
BUNDLE_KEYS = (
    "bundle", "reason", "trace_id", "ts", "pid", "version", "query",
    "events", "plan", "budget", "ladder", "memory", "conf",
)

_REDACTED = ("TOKEN", "SECRET", "PASSWORD", "PASSWD", "CREDENTIAL", "KEY")


def resolve_flight_enabled(conf: dict | None = None) -> bool:
    """The flight recorder is ON by default; `engine.flight_recorder` /
    NDS_FLIGHT_RECORDER set to off/0/false disables it (and restores the
    pre-flight zero-cost tracer default)."""
    v = None
    if conf:
        v = conf.get("engine.flight_recorder")
    if v is None:
        v = os.environ.get("NDS_FLIGHT_RECORDER")
    if v is None:
        return True
    return str(v).strip().lower() not in ("0", "off", "false", "no")


def resolve_ring_events(conf: dict | None = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.flight_ring_events")
    if v is None:
        v = os.environ.get("NDS_FLIGHT_RING_EVENTS")
    try:
        return max(int(v), 16) if v else DEFAULT_RING_EVENTS
    except (TypeError, ValueError):
        return DEFAULT_RING_EVENTS


def resolve_flight_dir(conf: dict | None = None) -> str:
    """Bundle destination: `engine.flight_dir` / NDS_FLIGHT_DIR, else the
    trace dir when one is configured (bundles sit next to the event logs
    they complement), else `<tempdir>/nds-flight` — a crashed run with NO
    observability configured still leaves its black box somewhere
    discoverable and documented."""
    v = None
    if conf:
        v = conf.get("engine.flight_dir")
    v = v or os.environ.get("NDS_FLIGHT_DIR")
    if v:
        return str(v)
    from .trace import resolve_trace_dir

    d = resolve_trace_dir(conf)
    if d:
        return d
    return os.path.join(tempfile.gettempdir(), "nds-flight")


class FlightRecorder:
    """Process-wide bounded event ring + incident-context notes.

    `record` is the hot path: ONE deque append (GIL-atomic, lock-free for
    the emitter — a concurrent `snapshot`/flush never blocks it). Notes
    (`note`, `note_plan`) hold the latest slow-changing context a bundle
    wants (last plan explains, budget verdicts) behind a short lock."""

    #: recent plan explains kept per process (keyed by query label): a
    #: bundle wants the FAILING statement's plan, and concurrent streams
    #: may be planning other statements at the same time
    MAX_PLANS = 8

    def __init__(self, capacity: int = DEFAULT_RING_EVENTS):
        # bounded-deque appends are atomic under the GIL; the hot path
        # stays lock-free on purpose (record rides every event emit)
        self._ring = deque(maxlen=capacity)  # nds-guarded-by: none
        self.capacity = capacity
        self.events_recorded = 0  # approximate under races  # nds-guarded-by: none
        self._lock = make_lock("FlightRecorder._lock")
        self._plans = OrderedDict()  # query label -> explain  # nds-guarded-by: _lock

    # -- hot path --------------------------------------------------------
    def record(self, ev: dict):
        self._ring.append(ev)
        self.events_recorded += 1  # telemetry only

    # -- incident context ------------------------------------------------
    def note_plan(self, query, explain):
        """Remember a statement's plan explain — a string, or a LAZY
        callable rendered only when a bundle actually flushes (the
        planner's hot path must not pay a string render per statement)."""
        with self._lock:
            key = str(query) if query is not None else "<unscoped>"
            self._plans[key] = explain
            self._plans.move_to_end(key)
            while len(self._plans) > self.MAX_PLANS:
                self._plans.popitem(last=False)

    def plan_for(self, query):
        with self._lock:
            key = str(query) if query is not None else "<unscoped>"
            explain = self._plans.get(key)
        if callable(explain):
            try:
                explain = explain()
            except Exception as exc:  # a stale plan must not kill a flush
                explain = f"<plan explain failed: {type(exc).__name__}>"
        return explain

    def snapshot(self) -> list:
        return list(self._ring)

    # -- bundles ---------------------------------------------------------
    def bundle(self, reason: str, trace_id=None, query=None, plan=None,
               budget=None, ladder=None, memory=None, conf=None,
               threads=None) -> dict:
        events = self.snapshot()
        if trace_id is None:
            # best effort: the newest ring event's stamped context
            for ev in reversed(events):
                if ev.get("trace_id"):
                    trace_id = ev["trace_id"]
                    break
        if trace_id is None:
            trace_id = f"flight-{os.getpid()}-{int(time.time())}"
        if plan is None:
            plan = self.plan_for(query)
        return {
            "bundle": 1,
            "reason": str(reason),
            "trace_id": str(trace_id),
            "ts": int(time.time() * 1000),
            "pid": os.getpid(),
            "version": __version__,
            "query": query,
            "events": events,
            "plan": plan,
            "budget": budget,
            "ladder": ladder,
            "memory": memory,
            "conf": redact_conf(conf) if conf else None,
            # suspected-deadlock evidence (engine/lockdebug.py watchdog):
            # {"stacks": {thread: [...frames]}, "locks": held-lock table}.
            # Optional-extra rather than a BUNDLE_KEYS key: most bundles
            # are not lock incidents, and the validate contract already
            # tolerates extras
            **({"threads": threads} if threads is not None else {}),
        }

    def flush(self, reason: str, trace_id=None, query=None, plan=None,
              budget=None, ladder=None, memory=None, conf=None,
              out_dir=None, threads=None):
        """Write the bundle atomically; returns its path, or None when the
        write failed (forensics must never take the run down — a broken
        flight dir is reported once to stdout, not raised)."""
        try:
            b = self.bundle(
                reason, trace_id=trace_id, query=query, plan=plan,
                budget=budget, ladder=ladder, memory=memory, conf=conf,
                threads=threads,
            )
            out_dir = out_dir or resolve_flight_dir()
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"{BUNDLE_PREFIX}{b['trace_id']}.json"
            )
            from ..io.fs import fs_open_atomic

            with fs_open_atomic(path, "w") as f:
                json.dump(b, f, default=str)
            print(f"obs: flight recorder wrote {path} ({reason})")
            return path
        except Exception as exc:
            print(f"obs: flight recorder flush failed ({exc})")
            return None


def redact_conf(conf: dict) -> dict:
    """Conf snapshot with credential-shaped keys dropped (same tag list
    the per-query report summary redacts its env with)."""
    return {
        str(k): str(v)
        for k, v in conf.items()
        if not any(tag in str(k).upper() for tag in _REDACTED)
    }


def validate_bundle(obj) -> list:
    """Structural problems with a failure bundle as strings (empty ==
    valid): the BUNDLE_KEYS contract plus event-schema validation of the
    ring (`profile --check` routes bundle paths here, so CI can assert a
    crash left a USABLE black box, not just a file)."""
    problems = []
    if not isinstance(obj, dict) or obj.get("bundle") != 1:
        return ["not a flight-recorder bundle (missing bundle: 1)"]
    for key in BUNDLE_KEYS:
        if key not in obj:
            problems.append(f"bundle missing key {key!r}")
    events = obj.get("events")
    if not isinstance(events, list):
        problems.append("bundle events is not a list")
    else:
        from .reader import validate_events

        problems.extend(
            f"ring {p}" for p in validate_events(events)
        )
    if not obj.get("trace_id"):
        problems.append("bundle has no trace_id")
    return problems


def read_bundle(path) -> dict:
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or obj.get("bundle") != 1:
        raise ValueError(f"{path}: not a flight-recorder bundle")
    return obj


def is_bundle_path(path) -> bool:
    base = os.path.basename(str(path))
    return base.startswith(BUNDLE_PREFIX) and base.endswith(".json")


# ---------------------------------------------------------------------------
# process-wide singleton (one black box per process, like the sink)
# ---------------------------------------------------------------------------

_SHARED_LOCK = make_lock("obs/flight.py:_SHARED_LOCK")
_SHARED = {}  # "recorder": FlightRecorder


def recorder(conf: dict | None = None):
    """The process-wide FlightRecorder, or None when disabled. Capacity
    resolves on first construction (one ring per process)."""
    if not resolve_flight_enabled(conf):
        return None
    with _SHARED_LOCK:
        rec = _SHARED.get("recorder")
        if rec is None:
            rec = _SHARED["recorder"] = FlightRecorder(
                resolve_ring_events(conf)
            )
        return rec


def reset_shared():
    """Drop the shared ring (test isolation; production processes keep
    theirs for the process lifetime)."""
    with _SHARED_LOCK:
        _SHARED.pop("recorder", None)
