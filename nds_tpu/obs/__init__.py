"""Observability subsystem: structured event tracing, live telemetry,
and post-run profiling.

`trace` — the Tracer (JSON-lines event log, `NDS_TRACE_DIR` /
`engine.trace_dir`, rotating at `engine.trace_rotate_bytes`), the golden
event schema, and thread-local binding.
`metrics` — the LIVE half: in-process counters/gauges/histograms fed
from `Tracer.emit` plus the /statusz run status (`engine.metrics_port`).
`httpserv` — the stdlib daemon-thread HTTP endpoint serving them.
`memwatch` — per-query device-memory/RSS high-water sampling + the
heartbeat liveness beacon.
`reader` — event-log parsing, validation, segment-chain reassembly,
fold-in summaries, operator aggregation, trace-dir compaction, and A/B
comparison (backing `nds_tpu/cli/profile.py`).
`flight` — the always-on flight recorder: a process-wide bounded event
ring every Tracer feeds, flushed as self-contained failure bundles on
watchdog fire / ladder exhaustion / crash / `/debug/flight`.
`critpath` — critical-path reconstruction: per-query wall attributed to
named causes (exchange-wait/skew, spill-io, ladder retries, ...) with
mesh straggler naming (backing `profile --critical-path`).
"""

from .trace import (  # noqa: F401
    EVENT_SCHEMA,
    Tracer,
    bind,
    current,
    resolve_trace_dir,
    tracer_from_conf,
)
from .memwatch import MemorySampler  # noqa: F401
from .metrics import (  # noqa: F401
    METRIC_KINDS,
    MetricsRegistry,
    MetricsSink,
    resolve_metrics_port,
    validate_exposition,
)
