"""Observability subsystem: structured event tracing + post-run profiling.

`trace` — the Tracer (JSON-lines event log, `NDS_TRACE_DIR` /
`engine.trace_dir`), the golden event schema, and thread-local binding.
`memwatch` — per-query device-memory/RSS high-water sampling.
`reader` — event-log parsing, validation, fold-in summaries, operator
aggregation, and A/B comparison (backing `nds_tpu/cli/profile.py`).
"""

from .trace import (  # noqa: F401
    EVENT_SCHEMA,
    Tracer,
    bind,
    current,
    resolve_trace_dir,
    tracer_from_conf,
)
from .memwatch import MemorySampler  # noqa: F401
