"""Live in-process telemetry: the metrics registry + run status behind
the `/metrics` and `/statusz` HTTP endpoints (obs/httpserv.py).

PR 3 built the POST-HOC half of observability (event log + profiler); this
module is the LIVE half, the reference analogue of Spark's driver-UI /
metrics system that the RAPIDS tools only post-process. A `MetricsSink`
rides the existing `Tracer.emit` seam: every event a traced run already
emits (op_span, query_span, exec_cache, ladder_rung, heartbeat, ...)
also updates thread-safe counters / gauges / bounded-bucket histograms
plus an in-flight run status, so a 30-minute bench or a hung stream is
inspectable while it runs instead of only after the log folds in.

Zero-cost contract (same as trace.py): with `engine.metrics_port` /
`NDS_METRICS_PORT` unset nothing here is constructed — `maybe_serve`
returns None after one conf lookup + one env read, `Tracer.sink` stays
None, and every hot instrumentation point still pays a single `is None`
check. With the port set but no trace dir, `tracer_from_conf` builds a
SINK-ONLY tracer (no file, no in-memory list) so the live counters work
without paying event-log disk.

Metric-taxonomy contract: every metric family name derives from the
EVENT_SCHEMA kind that feeds it — METRIC_KINDS below maps family ->
source kind, and the `trace-event-schema` lint rule enforces both that
the kind exists and that the family name embeds it, so live metric names
cannot drift from the event taxonomy (no free-floating names).

The sink and server are process-wide singletons on purpose: a throughput
run's per-stream sessions share one exposition endpoint (counters
aggregate across streams, like Spark executors reporting into one driver
UI), and subprocess children that inherit NDS_METRICS_PORT but lose the
bind race just keep their sink un-exposed (observability never takes the
benchmark down).
"""

from __future__ import annotations

import os
import re
import threading
import time

from .trace import EVENT_SCHEMA
from ..engine.lockdebug import make_lock

#: metric family -> the EVENT_SCHEMA kind that feeds it. The lint rule
#: `trace-event-schema` (analysis/lint.py) enforces that every value is a
#: schema kind and every key embeds its kind; the registry refuses names
#: outside this map at runtime (the belt to lint's suspenders).
METRIC_KINDS = {
    "nds_op_span_total": "op_span",
    "nds_op_span_ms_total": "op_span",
    "nds_query_span_total": "query_span",
    "nds_query_span_ms_total": "query_span",
    "nds_query_span_dur_ms": "query_span",          # histogram
    "nds_query_span_mem_hw_bytes": "query_span",    # gauge (high-water)
    "nds_plan_cache_total": "plan_cache",
    "nds_catalog_load_total": "catalog_load",
    "nds_exec_cache_total": "exec_cache",
    "nds_aot_cache_total": "aot_cache",
    "nds_aot_cache_bytes_total": "aot_cache",
    "nds_aot_cache_ms_total": "aot_cache",
    "nds_pipeline_span_total": "pipeline_span",
    "nds_kernel_span_total": "kernel_span",
    "nds_kernel_span_ms_total": "kernel_span",
    "nds_blocked_union_total": "blocked_union",
    "nds_blocked_union_windows_total": "blocked_union",
    "nds_exchange_total": "exchange",
    "nds_exchange_bytes_total": "exchange",
    "nds_exchange_retries_total": "exchange",
    "nds_exchange_skew": "exchange",                # gauge (latest ratio)
    "nds_mesh_fallback_total": "mesh_fallback",
    "nds_spill_total": "spill",
    "nds_spill_bytes_in_total": "spill",
    "nds_spill_bytes_out_total": "spill",
    "nds_spill_evictions_total": "spill",
    "nds_lake_commit_total": "lake_commit",
    "nds_lake_commit_attempts_total": "lake_commit",
    "nds_lake_vacuum_total": "lake_vacuum",
    "nds_lake_vacuum_files_total": "lake_vacuum",
    "nds_ingest_chunk_total": "ingest_chunk",
    "nds_ingest_chunk_rows_total": "ingest_chunk",
    "nds_ingest_chunk_decode_ms_total": "ingest_chunk",
    "nds_ingest_chunk_commit_ms_total": "ingest_chunk",
    "nds_scan_prune_total": "scan_prune",
    "nds_scan_prune_files_total": "scan_prune",
    "nds_scan_prune_files_pruned_total": "scan_prune",
    "nds_catalog_commit_total": "catalog_commit",
    "nds_catalog_commit_ms_total": "catalog_commit",
    "nds_catalog_lease_total": "catalog_lease",
    "nds_fault_injected_total": "fault_injected",
    "nds_ladder_rung_total": "ladder_rung",
    "nds_watchdog_fire_total": "watchdog_fire",
    "nds_io_retry_total": "io_retry",
    "nds_phase_total": "phase",
    "nds_child_stream_total": "child_stream",
    "nds_plan_verify_total": "plan_verify",
    "nds_plan_budget_total": "plan_budget",
    "nds_plan_feedback_total": "plan_feedback",
    "nds_plan_feedback_overrides_total": "plan_feedback",
    "nds_plan_feedback_err_median": "plan_feedback",  # gauge (|log| median)
    "nds_mem_watermark_total": "mem_watermark",
    "nds_heartbeat_total": "heartbeat",
    "nds_heartbeat_rss_bytes": "heartbeat",         # gauge (latest)
    "nds_heartbeat_elapsed_ms": "heartbeat",        # gauge (latest)
    "nds_lock_contention_total": "lock_contention",
    "nds_lock_contention_wait_ms": "lock_contention",  # histogram
    "nds_serve_request_total": "serve_request",
    "nds_serve_request_ms_total": "serve_request",
    "nds_serve_request_dur_ms": "serve_request",    # histogram (p99 scrape)
    "nds_serve_request_rows_total": "serve_request",
    "nds_serve_request_bytes_total": "serve_request",
    "nds_route_request_total": "route_request",
    "nds_route_request_ms_total": "route_request",
    "nds_route_request_dur_ms": "route_request",    # histogram (fleet p99)
    "nds_route_retry_total": "route_retry",
}

#: bounded histogram buckets (ms): an hour-long query lands in +Inf, the
#: bucket count never grows past this tuple (the "bounded-bucket" half of
#: the registry contract — no per-value allocation on the hot path)
HIST_BUCKETS_MS = (
    5.0, 20.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
    15000.0, 60000.0,
)


def resolve_metrics_port(conf: dict | None = None):
    """The metrics endpoint port from conf `engine.metrics_port`, else
    NDS_METRICS_PORT; None (telemetry disabled — the default) when neither
    is set. 0 binds an OS-assigned ephemeral port (read it back from
    `MetricsServer.port` / `active_server()` — the CI e2e mode)."""
    v = None
    if conf:
        v = conf.get("engine.metrics_port")
    if v is None:
        v = os.environ.get("NDS_METRICS_PORT")
    if v is None or str(v).strip().lower() in ("", "off", "none"):
        return None
    try:
        port = int(v)
    except (TypeError, ValueError):
        return None
    return port if port >= 0 else None


def _esc(value) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


class MetricsRegistry:
    """Thread-safe counters, gauges, and bounded-bucket histograms with
    Prometheus text exposition (`render`).

    Families must be registered in METRIC_KINDS (names derive from event
    kinds — the lint-enforced taxonomy contract); series within a family
    are keyed by their sorted label items. All mutators take one short
    lock; there is no per-series allocation after first touch."""

    def __init__(self):
        self._lock = make_lock("MetricsRegistry._lock")
        # (name, labels) -> float                # nds-guarded-by: _lock
        self._counters = {}
        # (name, labels) -> float                # nds-guarded-by: _lock
        self._gauges = {}
        # (name, labels) -> [bucket cts, +Inf]   # nds-guarded-by: _lock
        self._hists = {}
        # (name, labels) -> (sum, count)         # nds-guarded-by: _lock
        self._hist_sum = {}
        # family -> counter|gauge|histogram      # nds-guarded-by: _lock
        self._types = {}

    @staticmethod
    def _key(name, labels):
        if name not in METRIC_KINDS:
            raise ValueError(
                f"metric family {name!r} is not registered in "
                f"obs/metrics.py:METRIC_KINDS (names must derive from "
                f"EVENT_SCHEMA kinds)"
            )
        return (name, tuple(sorted(labels.items())))

    def inc(self, name, value=1.0, **labels):
        key = self._key(name, labels)
        with self._lock:
            self._types.setdefault(name, "counter")
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def set_gauge(self, name, value, **labels):
        key = self._key(name, labels)
        with self._lock:
            self._types.setdefault(name, "gauge")
            self._gauges[key] = float(value)

    def max_gauge(self, name, value, **labels):
        """Gauge that only ratchets upward (high-water marks)."""
        key = self._key(name, labels)
        with self._lock:
            self._types.setdefault(name, "gauge")
            cur = self._gauges.get(key)
            if cur is None or float(value) > cur:
                self._gauges[key] = float(value)

    def observe(self, name, value, **labels):
        key = self._key(name, labels)
        v = float(value)
        with self._lock:
            self._types.setdefault(name, "histogram")
            counts = self._hists.get(key)
            if counts is None:
                counts = self._hists[key] = [0] * (len(HIST_BUCKETS_MS) + 1)
                self._hist_sum[key] = (0.0, 0)
            for i, bound in enumerate(HIST_BUCKETS_MS):
                if v <= bound:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s, n = self._hist_sum[key]
            self._hist_sum[key] = (s + v, n + 1)

    # -- reads -----------------------------------------------------------
    def counter_value(self, name, **labels) -> float:
        with self._lock:
            return self._counters.get(self._key(name, labels), 0.0)

    def counter_series(self, name) -> dict:
        """{label-items-tuple: value} for one counter family."""
        with self._lock:
            return {
                k[1]: v for k, v in self._counters.items() if k[0] == name
            }

    # -- exposition ------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format (0.0.4) of every series."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: list(v) for k, v in self._hists.items()}
            hist_sum = dict(self._hist_sum)
            types = dict(self._types)
        out = []

        def fmt(value):
            f = float(value)
            return str(int(f)) if f == int(f) else repr(f)

        def series_line(name, labels, value, suffix="", extra=()):
            items = tuple(labels) + tuple(extra)
            lbl = (
                "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in items) + "}"
                if items
                else ""
            )
            out.append(f"{name}{suffix}{lbl} {fmt(value)}")

        for name in sorted(types):
            kind = types[name]
            out.append(f"# TYPE {name} {kind}")
            if kind == "counter":
                for (n, labels), v in sorted(counters.items()):
                    if n == name:
                        series_line(name, labels, v)
            elif kind == "gauge":
                for (n, labels), v in sorted(gauges.items()):
                    if n == name:
                        series_line(name, labels, v)
            else:  # histogram
                for (n, labels), counts in sorted(hists.items()):
                    if n != name:
                        continue
                    cum = 0
                    for i, bound in enumerate(HIST_BUCKETS_MS):
                        cum += counts[i]
                        series_line(name, labels, cum, "_bucket",
                                    extra=(("le", fmt(bound)),))
                    cum += counts[-1]
                    series_line(name, labels, cum, "_bucket",
                                extra=(("le", "+Inf"),))
                    s, cnt = hist_sum[(n, labels)]
                    series_line(name, labels, s, "_sum")
                    series_line(name, labels, cnt, "_count")
        return "\n".join(out) + ("\n" if out else "")


_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL_RE = rf'{_NAME_RE}="(?:[^"\\\n]|\\["\\n])*"'
_VALUE_RE = r"(?:[+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|[+-]Inf|NaN)"
_SAMPLE_RE = re.compile(
    rf"^{_NAME_RE}(?:\{{{_LABEL_RE}(?:,{_LABEL_RE})*\}})? {_VALUE_RE}$"
)
_COMMENT_RE = re.compile(rf"^# (?:TYPE|HELP) {_NAME_RE}( .*)?$")


def validate_exposition(text: str) -> list:
    """Problems with a /metrics payload as strings (empty == valid):
    every line must be a well-formed comment or sample, and every sample's
    family must be TYPE-declared first. The CI e2e scrapes mid-run and
    fails on any finding (the exposition-format half of the live gate)."""
    problems = []
    declared = set()
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _COMMENT_RE.match(line)
            if not m:
                problems.append(f"line {i}: malformed comment: {line[:120]!r}")
            else:
                declared.add(line.split()[2])
            continue
        if not _SAMPLE_RE.match(line):
            problems.append(f"line {i}: malformed sample: {line[:120]!r}")
            continue
        family = re.split(r"[{ ]", line, maxsplit=1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", family)
        if family not in declared and base not in declared:
            problems.append(
                f"line {i}: sample {family!r} before its # TYPE declaration"
            )
    return problems


class MetricsSink:
    """Event -> live-telemetry bridge: `record(ev)` (called by
    `Tracer.emit` for every event) updates the registry and the in-flight
    run status; `status_snapshot()` is the /statusz payload.

    `record` must never take the run down: handler failures are swallowed
    (the same contract as a broken trace dir disabling its tracer)."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self._slock = make_lock("MetricsSink._slock")
        self._status = {  # nds-guarded-by: _slock
            "pid": os.getpid(),
            "started_ts_ms": int(time.time() * 1000),
            "phase": None,
            "last_phase": None,
            "queries_completed": 0,
            "queries_failed": 0,
            "heartbeat_ts_ms": None,
            "rss_bytes": None,
            "mem_hw_bytes": None,
            "mem_source": None,
        }
        # keyed (app id, query name, request id): thread-mode throughput
        # streams share this process-wide sink and may run the SAME query
        # concurrently — a name-only key would let stream B's start
        # clobber stream A's record and A's finish retire B's (hiding a
        # live hang). The request id (serve mode) extends the same
        # guarantee to one SESSION: two tenants re-running one template
        # concurrently share the app id, so only the per-request id keeps
        # their in-flight records apart. Non-serve callers pass None and
        # keep the (app, query) semantics unchanged.
        self._in_flight = {}  # nds-guarded-by: _slock
        # router-process hook (serve/router.py): a callable returning the
        # live fleet view (replica health, degraded capabilities, tenant
        # in-flight) merged into /statusz's "fleet" section at snapshot
        # time — the router owns that state, the sink only tallies events
        # single-reference swap, installed once at router startup;
        # readers tolerate either value
        self._fleet_provider = None  # nds-guarded-by: none

    def set_fleet_provider(self, fn):
        """Install the router's fleet-snapshot callable (or None to
        clear). Called OUTSIDE the status lock at snapshot time."""
        self._fleet_provider = fn

    # -- direct harness hooks -------------------------------------------
    def query_started(self, name, app=None, request_id=None):
        """BenchReport marks the query in flight BEFORE the first attempt
        (query_span only exists at the end — too late for /statusz).
        `app` is the emitting tracer's app id, the same value the query's
        events will carry, so event handlers find this record;
        `request_id` (serve mode) disambiguates concurrent identical
        queries on one session."""
        with self._slock:
            self._in_flight[(app, str(name), request_id)] = {
                "query": str(name),
                "app": app,
                **({"request_id": request_id} if request_id else {}),
                "started_ts_ms": int(time.time() * 1000),
                "_mono": time.perf_counter(),
                "attempt": 1,
                "ladder": [],
            }

    @staticmethod
    def _flight_key(ev):
        return (ev.get("app"), str(ev.get("query")), ev.get("request_id"))

    # -- event dispatch --------------------------------------------------
    def record(self, ev: dict):
        handler = _HANDLERS.get(ev.get("kind"))
        if handler is None:
            return
        try:
            handler(self, ev)
        except Exception:
            pass  # live telemetry must never take the benchmark down

    def _h_op_span(self, ev):
        node = str(ev.get("node"))
        self.registry.inc("nds_op_span_total", node=node)
        self.registry.inc(
            "nds_op_span_ms_total", float(ev.get("dur_ms") or 0.0), node=node
        )

    def _h_query_span(self, ev):
        status = str(ev.get("status"))
        dur = float(ev.get("dur_ms") or 0.0)
        self.registry.inc("nds_query_span_total", status=status)
        self.registry.inc("nds_query_span_ms_total", dur)
        self.registry.observe("nds_query_span_dur_ms", dur)
        if ev.get("mem_hw_bytes") is not None:
            self.registry.max_gauge(
                "nds_query_span_mem_hw_bytes", int(ev["mem_hw_bytes"])
            )
        with self._slock:
            self._in_flight.pop(self._flight_key(ev), None)
            if status == "Failed":
                self._status["queries_failed"] += 1
            else:
                self._status["queries_completed"] += 1
            if ev.get("mem_hw_bytes") is not None:
                cur = self._status["mem_hw_bytes"] or 0
                if int(ev["mem_hw_bytes"]) > cur:
                    self._status["mem_hw_bytes"] = int(ev["mem_hw_bytes"])
                    self._status["mem_source"] = ev.get("mem_source")
            if isinstance(ev.get("mem_hw_per_device"), list):
                self._merge_device_hw_locked(ev["mem_hw_per_device"])

    def _h_plan_cache(self, ev):
        self.registry.inc(
            "nds_plan_cache_total", result="hit" if ev.get("hit") else "miss"
        )

    def _h_catalog_load(self, ev):
        self.registry.inc(
            "nds_catalog_load_total", cache=str(ev.get("cache"))
        )

    def _h_exec_cache(self, ev):
        self.registry.inc(
            "nds_exec_cache_total", result="hit" if ev.get("hit") else "miss"
        )

    def _h_aot_cache(self, ev):
        op = str(ev.get("op"))
        result = str(ev.get("result"))
        self.registry.inc("nds_aot_cache_total", op=op, result=result)
        if ev.get("bytes") is not None:
            self.registry.inc(
                "nds_aot_cache_bytes_total", int(ev["bytes"]), op=op
            )
        if ev.get("dur_ms") is not None:
            self.registry.inc(
                "nds_aot_cache_ms_total", float(ev["dur_ms"]), op=op
            )

    def _h_pipeline_span(self, ev):
        self.registry.inc(
            "nds_pipeline_span_total",
            fused="true" if ev.get("fused") else "false",
        )

    def _h_kernel_span(self, ev):
        kernel = str(ev.get("kernel"))
        self.registry.inc("nds_kernel_span_total", kernel=kernel)
        self.registry.inc(
            "nds_kernel_span_ms_total", float(ev.get("dur_ms") or 0.0),
            kernel=kernel,
        )

    def _h_exchange(self, ev):
        self.registry.inc("nds_exchange_total", op=str(ev.get("op")))
        self.registry.inc(
            "nds_exchange_bytes_total", int(ev.get("bytes_moved") or 0)
        )
        self.registry.inc(
            "nds_exchange_retries_total", int(ev.get("retries") or 0)
        )
        try:
            self.registry.set_gauge(
                "nds_exchange_skew", float(ev.get("skew") or 1.0)
            )
        except (TypeError, ValueError):
            pass
        with self._slock:
            mesh = self._status.setdefault("mesh", {})
            mesh["last_exchange"] = {
                "op": ev.get("op"),
                "partitions": ev.get("partitions"),
                "bytes_moved": ev.get("bytes_moved"),
                "skew": ev.get("skew"),
                "retries": ev.get("retries"),
                "ts": ev.get("ts"),
                **({"per_device": list(ev["per_device"])}
                   if isinstance(ev.get("per_device"), list) else {}),
            }

    def _merge_device_hw_locked(self, per_dev):
        """Element-wise max-merge per-device HBM samples into the mesh
        section (caller holds _slock)."""
        mesh = self._status.setdefault("mesh", {})
        hw = mesh.setdefault("device_mem_hw", [])
        for i, b in enumerate(per_dev):
            b = int(b)
            if i < len(hw):
                if b > hw[i]:
                    hw[i] = b
            else:
                hw.append(b)

    def _h_mesh_fallback(self, ev):
        self.registry.inc(
            "nds_mesh_fallback_total", table=str(ev.get("table"))
        )

    def _h_spill(self, ev):
        self.registry.inc("nds_spill_total", op=str(ev.get("op")))
        self.registry.inc(
            "nds_spill_bytes_in_total", int(ev.get("bytes_in") or 0)
        )
        self.registry.inc(
            "nds_spill_bytes_out_total", int(ev.get("bytes_out") or 0)
        )
        self.registry.inc(
            "nds_spill_evictions_total", int(ev.get("evictions") or 0)
        )

    def _h_blocked_union(self, ev):
        self.registry.inc("nds_blocked_union_total")
        self.registry.inc(
            "nds_blocked_union_windows_total", int(ev.get("windows") or 0)
        )

    def _h_lake_commit(self, ev):
        status = "conflict" if ev.get("conflict") else (
            "rebased" if ev.get("rebased") else "ok"
        )
        self.registry.inc(
            "nds_lake_commit_total",
            operation=str(ev.get("operation")), status=status,
        )
        self.registry.inc(
            "nds_lake_commit_attempts_total", int(ev.get("attempts") or 1)
        )

    def _h_lake_vacuum(self, ev):
        self.registry.inc("nds_lake_vacuum_total", table=str(ev.get("table")))
        self.registry.inc(
            "nds_lake_vacuum_files_total", int(ev.get("files_removed") or 0)
        )

    def _layout_status_locked(self, ev):
        """The /statusz `layout` section (caller holds _slock): the data-
        layout subsystem's live tallies — ingest chunk progress on the
        fill side, zone-map pruning effectiveness on the scan side.
        Scalars only, so status_snapshot's one-level copy suffices."""
        lay = self._status.setdefault("layout", {
            "ingest_chunks": 0, "ingest_rows": 0, "ingest_skipped": 0,
            "last_ingest_table": None, "prunes": 0, "files_seen": 0,
            "files_pruned": 0, "last_prune_table": None,
            "last_ts_ms": None,
        })
        lay["last_ts_ms"] = ev.get("ts")
        return lay

    def _h_ingest_chunk(self, ev):
        table = str(ev.get("table"))
        skipped = bool(ev.get("skipped"))
        self.registry.inc(
            "nds_ingest_chunk_total", table=table,
            status="skipped" if skipped else "ok",
        )
        self.registry.inc(
            "nds_ingest_chunk_rows_total", int(ev.get("rows") or 0)
        )
        self.registry.inc(
            "nds_ingest_chunk_decode_ms_total",
            float(ev.get("decode_ms") or 0.0),
        )
        self.registry.inc(
            "nds_ingest_chunk_commit_ms_total",
            float(ev.get("commit_ms") or 0.0),
        )
        with self._slock:
            lay = self._layout_status_locked(ev)
            lay["ingest_chunks"] += 1
            lay["ingest_rows"] += int(ev.get("rows") or 0)
            if skipped:
                lay["ingest_skipped"] += 1
            lay["last_ingest_table"] = ev.get("table")

    def _h_scan_prune(self, ev):
        self.registry.inc(
            "nds_scan_prune_total", table=str(ev.get("table"))
        )
        self.registry.inc(
            "nds_scan_prune_files_total", int(ev.get("files_total") or 0)
        )
        self.registry.inc(
            "nds_scan_prune_files_pruned_total",
            int(ev.get("files_pruned") or 0),
        )
        with self._slock:
            lay = self._layout_status_locked(ev)
            lay["prunes"] += 1
            lay["files_seen"] += int(ev.get("files_total") or 0)
            lay["files_pruned"] += int(ev.get("files_pruned") or 0)
            lay["last_prune_table"] = ev.get("table")

    def _catalog_status_locked(self, ev):
        """The /statusz `catalog` section (caller holds _slock): scalar
        tallies only, so status_snapshot's one-level dict copy suffices."""
        cat = self._status.setdefault("catalog", {
            "backend": None, "commits": 0, "conflicts": 0, "fenced": 0,
            "rolled_back": 0, "unreachable": 0, "expired": 0,
            "lease_ops": 0, "fence": None, "last_table": None,
            "last_version": None, "last_ts_ms": None,
        })
        cat["backend"] = ev.get("backend")
        cat["last_ts_ms"] = ev.get("ts")
        return cat

    def _h_catalog_commit(self, ev):
        outcome = str(ev.get("outcome"))
        backend = str(ev.get("backend"))
        self.registry.inc(
            "nds_catalog_commit_total", backend=backend, outcome=outcome
        )
        if ev.get("dur_ms") is not None:
            self.registry.inc(
                "nds_catalog_commit_ms_total", float(ev["dur_ms"]),
                backend=backend,
            )
        with self._slock:
            cat = self._catalog_status_locked(ev)
            key = {
                "ok": "commits", "conflict": "conflicts",
                "fenced": "fenced", "rolled_back": "rolled_back",
                "unreachable": "unreachable", "expired": "expired",
            }.get(outcome)
            if key:
                cat[key] += 1
            cat["last_table"] = ev.get("table")
            if outcome == "ok":
                cat["last_version"] = ev.get("version")

    def _h_catalog_lease(self, ev):
        self.registry.inc(
            "nds_catalog_lease_total",
            op=str(ev.get("op")), outcome=str(ev.get("outcome")),
        )
        with self._slock:
            cat = self._catalog_status_locked(ev)
            cat["lease_ops"] += 1
            if ev.get("fence") is not None:
                cat["fence"] = ev.get("fence")

    def _h_fault_injected(self, ev):
        self.registry.inc(
            "nds_fault_injected_total", kind=str(ev.get("fault_kind"))
        )

    def _h_ladder_rung(self, ev):
        self.registry.inc("nds_ladder_rung_total", rung=str(ev.get("rung")))
        with self._slock:
            rec = self._in_flight.get(self._flight_key(ev))
            if rec is not None:
                rec["attempt"] += 1
                rec["ladder"].append(str(ev.get("rung")))

    def _h_watchdog_fire(self, ev):
        self.registry.inc("nds_watchdog_fire_total")

    def _h_io_retry(self, ev):
        self.registry.inc("nds_io_retry_total")

    def _h_phase(self, ev):
        name = str(ev.get("phase"))
        event = str(ev.get("event"))
        self.registry.inc("nds_phase_total", phase=name, event=event)
        with self._slock:
            if event == "begin":
                self._status["phase"] = {
                    "name": name,
                    "index": ev.get("index"),
                    "total": ev.get("total"),
                    "since_ts_ms": ev.get("ts"),
                }
            else:
                cur = self._status.get("phase")
                if cur and cur.get("name") == name:
                    self._status["phase"] = None
                self._status["last_phase"] = {
                    "name": name, "status": ev.get("status"),
                }

    def _h_child_stream(self, ev):
        self.registry.inc("nds_child_stream_total")

    def _h_plan_verify(self, ev):
        self.registry.inc(
            "nds_plan_verify_total", ok="true" if ev.get("ok") else "false"
        )

    def _h_plan_budget(self, ev):
        self.registry.inc(
            "nds_plan_budget_total", verdict=str(ev.get("verdict"))
        )

    #: |log(est/actual)| bucket edges for the budgeter-accuracy median.
    #: Bounded on purpose: a long-lived service records one sample per
    #: executed feedback node forever, and an exact sample list would grow
    #: without limit. 0.69 ~= a 2x miss, 2.3 ~= a 10x miss.
    FEEDBACK_ERR_EDGES = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0)

    def _feedback_err_median_locked(self, fb):
        """Median |log(est/actual)| from the bounded bucket tallies —
        reported as the upper edge of the bucket the median sample falls
        in (the overflow bucket reports 2x the last edge). Caller holds
        _slock."""
        n = fb.get("err_n") or 0
        if not n:
            return None
        half = (n + 1) // 2
        acc = 0
        for i, c in enumerate(fb["err_buckets"]):
            acc += c
            if acc >= half:
                edges = self.FEEDBACK_ERR_EDGES
                return edges[i] if i < len(edges) else edges[-1] * 2
        return None

    def _h_plan_feedback(self, ev):
        op = str(ev.get("op"))
        self.registry.inc(
            "nds_plan_feedback_total", op=op, result=str(ev.get("result"))
        )
        if ev.get("overrides"):
            self.registry.inc(
                "nds_plan_feedback_overrides_total", int(ev["overrides"])
            )
        med = None
        with self._slock:
            fb = self._status.setdefault("feedback", {
                "lookups": 0, "hits": 0, "records": 0, "overrides": 0,
                "err_n": 0,
                "err_buckets": [0] * (len(self.FEEDBACK_ERR_EDGES) + 1),
                "mode": None, "last_verdict": None,
            })
            if op in ("consume", "annotate"):
                # budget-time event: one per budgeted plan, carries the
                # store's lookup/hit tallies for that plan
                fb["lookups"] += int(ev.get("lookups") or 0)
                fb["hits"] += int(ev.get("hits") or 0)
                fb["overrides"] += int(ev.get("overrides") or 0)
                if ev.get("mode") is not None:
                    fb["mode"] = str(ev["mode"])
                if ev.get("verdict") is not None:
                    fb["last_verdict"] = str(ev["verdict"])
            elif op == "record":
                fb["records"] += 1
                err = ev.get("abs_log_err")
                if err is not None:
                    e = float(err)
                    fb["err_n"] += 1
                    for i, hi in enumerate(self.FEEDBACK_ERR_EDGES):
                        if e <= hi:
                            fb["err_buckets"][i] += 1
                            break
                    else:
                        fb["err_buckets"][-1] += 1
                    med = self._feedback_err_median_locked(fb)
        if med is not None:
            self.registry.set_gauge("nds_plan_feedback_err_median", med)

    def _h_mem_watermark(self, ev):
        self.registry.inc("nds_mem_watermark_total")

    #: distinct tenants tracked before new ones fold into "__other__":
    #: the tenant header is client-controlled, and unbounded label values
    #: would grow process memory + Prometheus series cardinality forever
    #: on a long-lived service
    MAX_TENANT_SERIES = 64

    def _h_serve_request(self, ev):
        tenant = str(ev.get("tenant"))
        with self._slock:
            known = self._status.get("tenants") or {}
            if (
                tenant not in known
                and len(known) >= self.MAX_TENANT_SERIES
            ):
                tenant = "__other__"
        status = str(ev.get("status"))
        dur = float(ev.get("dur_ms") or 0.0)
        self.registry.inc(
            "nds_serve_request_total", tenant=tenant, status=status
        )
        self.registry.inc("nds_serve_request_ms_total", dur, tenant=tenant)
        # unlabeled histogram on purpose: the serve_bench p99 scrape wants
        # ONE bucket series to invert, not a per-tenant product
        self.registry.observe("nds_serve_request_dur_ms", dur)
        if ev.get("rows") is not None:
            self.registry.inc(
                "nds_serve_request_rows_total", int(ev["rows"]),
                tenant=tenant,
            )
        if ev.get("bytes") is not None:
            self.registry.inc(
                "nds_serve_request_bytes_total", int(ev["bytes"]),
                tenant=tenant,
            )
        with self._slock:
            tenants = self._status.setdefault("tenants", {})
            t = tenants.setdefault(tenant, {
                "requests": 0, "completed": 0, "failed": 0, "rejected": 0,
                "shed": 0, "draining": 0, "degraded": 0, "rows": 0,
                "bytes": 0, "ms_total": 0.0,
                "exec_cache_hits": 0, "exec_cache_lookups": 0,
                "plan_cache_hits": 0, "plan_cache_lookups": 0,
            })
            t["requests"] += 1
            if status in t:
                t[status] += 1
            if ev.get("verdict") in ("blocked", "spill", "over"):
                t["degraded"] += 1
            t["rows"] += int(ev.get("rows") or 0)
            t["bytes"] += int(ev.get("bytes") or 0)
            t["ms_total"] = round(t["ms_total"] + dur, 3)
            for k in ("exec_cache_hits", "exec_cache_lookups",
                      "plan_cache_hits", "plan_cache_lookups"):
                t[k] += int(ev.get(k) or 0)

    def _h_route_request(self, ev):
        """Router-edge accounting (serve/router.py): the same tenant
        folding bound as serve_request — the fleet tenants section is
        the per-tenant FLEET counter home (satellite: the per-replica
        serve_tenant_cap's router-enforced equivalent reports here)."""
        tenant = str(ev.get("tenant"))
        with self._slock:
            fleet = self._status.get("fleet") or {}
            known = fleet.get("tenants") or {}
            if (
                tenant not in known
                and len(known) >= self.MAX_TENANT_SERIES
            ):
                tenant = "__other__"
        status = str(ev.get("status"))
        dur = float(ev.get("dur_ms") or 0.0)
        self.registry.inc(
            "nds_route_request_total", tenant=tenant, status=status
        )
        self.registry.inc("nds_route_request_ms_total", dur, tenant=tenant)
        # unlabeled on purpose, like nds_serve_request_dur_ms: the fleet
        # bench p99 scrape inverts ONE bucket series
        self.registry.observe("nds_route_request_dur_ms", dur)
        with self._slock:
            fleet = self._status.setdefault("fleet", {
                "requests": 0, "edge_rejected": 0, "retries": 0,
                "tenants": {},
            })
            fleet["requests"] += 1
            if status == "rejected" or (
                status == "shed" and ev.get("replica") is None
            ):
                # answered at the edge: no replica worker slot consumed
                fleet["edge_rejected"] += 1
            tenants = fleet.setdefault("tenants", {})
            t = tenants.setdefault(tenant, {
                "requests": 0, "completed": 0, "failed": 0, "rejected": 0,
                "shed": 0, "draining": 0, "retries": 0, "ms_total": 0.0,
            })
            t["requests"] += 1
            if status in t:
                t[status] += 1
            t["retries"] += int(ev.get("retries") or 0)
            t["ms_total"] = round(t["ms_total"] + dur, 3)

    def _h_route_retry(self, ev):
        self.registry.inc(
            "nds_route_retry_total", reason=str(ev.get("reason"))
        )
        with self._slock:
            fleet = self._status.setdefault("fleet", {
                "requests": 0, "edge_rejected": 0, "retries": 0,
                "tenants": {},
            })
            fleet["retries"] += 1

    def _h_lock_contention(self, ev):
        self.registry.inc(
            "nds_lock_contention_total", lock=str(ev.get("lock") or "?")
        )
        # unlabeled histogram on purpose (like the request-duration one):
        # the question is "how long do waits run fleet-wide", per-lock
        # attribution comes from the counter + the event stream
        self.registry.observe(
            "nds_lock_contention_wait_ms", float(ev.get("wait_ms") or 0.0)
        )

    def _h_heartbeat(self, ev):
        self.registry.inc("nds_heartbeat_total")
        if ev.get("rss_bytes") is not None:
            self.registry.set_gauge(
                "nds_heartbeat_rss_bytes", int(ev["rss_bytes"])
            )
        self.registry.set_gauge(
            "nds_heartbeat_elapsed_ms", float(ev.get("elapsed_ms") or 0.0)
        )
        with self._slock:
            self._status["heartbeat_ts_ms"] = ev.get("ts")
            if ev.get("rss_bytes") is not None:
                self._status["rss_bytes"] = int(ev["rss_bytes"])
            if isinstance(ev.get("dev_bytes"), list):
                self._merge_device_hw_locked(ev["dev_bytes"])
            rec = self._in_flight.get(self._flight_key(ev))
            if rec is not None:
                rec["heartbeat_elapsed_ms"] = ev.get("elapsed_ms")

    # -- /statusz --------------------------------------------------------
    def _hit_rate(self, family, hit_label, hit_value):
        series = self.registry.counter_series(family)
        total = sum(series.values())
        hits = sum(
            v for labels, v in series.items()
            if (hit_label, hit_value) in labels
        )
        return {
            "hits": int(hits),
            "total": int(total),
            "rate": round(hits / total, 4) if total else None,
        }

    def status_snapshot(self) -> dict:
        now_ms = int(time.time() * 1000)
        now_mono = time.perf_counter()
        with self._slock:
            st = {
                k: (dict(v) if isinstance(v, dict) else v)
                for k, v in self._status.items()
            }
            if "mesh" in st:
                # deep-copy: the live list/dict keep mutating under this
                # lock after the snapshot escapes it
                mesh = self._status["mesh"]
                st["mesh"] = {
                    k: (list(v) if isinstance(v, list)
                        else dict(v) if isinstance(v, dict) else v)
                    for k, v in mesh.items()
                }
            if "tenants" in st:
                # deep-copy + derive per-tenant cache hit rates (the
                # shallow copy above would alias the live tallies)
                tenants = {}
                for name, t in self._status["tenants"].items():
                    t = dict(t)
                    for fam in ("exec_cache", "plan_cache"):
                        total = t.get(f"{fam}_lookups") or 0
                        t[f"{fam}_hit_rate"] = (
                            round(t[f"{fam}_hits"] / total, 4)
                            if total else None
                        )
                    tenants[name] = t
                st["tenants"] = tenants
            if "fleet" in st:
                # deep-copy: the tallies keep mutating under this lock
                fleet = self._status["fleet"]
                st["fleet"] = {
                    k: (
                        {tn: dict(t) for tn, t in v.items()}
                        if k == "tenants" else v
                    )
                    for k, v in fleet.items()
                }
            if "feedback" in st:
                # deep-copy: err_buckets is a live list mutating under
                # this lock after the snapshot escapes it
                fb = dict(self._status["feedback"])
                fb["err_buckets"] = list(fb["err_buckets"])
                fb["err_median"] = self._feedback_err_median_locked(fb)
                st["feedback"] = fb
            in_flight = []
            for rec in self._in_flight.values():
                rec = dict(rec)
                rec["elapsed_ms"] = round(
                    (now_mono - rec.pop("_mono")) * 1000, 1
                )
                rec["ladder"] = list(rec["ladder"])
                in_flight.append(rec)
        in_flight.sort(key=lambda r: -r["elapsed_ms"])
        st["in_flight"] = in_flight
        # the longest-running in-flight query is the hang-detection view
        st["query"] = in_flight[0] if in_flight else None
        st["caches"] = {
            "exec_cache": self._hit_rate("nds_exec_cache_total", "result", "hit"),
            "plan_cache": self._hit_rate("nds_plan_cache_total", "result", "hit"),
            "catalog": self._hit_rate("nds_catalog_load_total", "cache", "hit"),
        }
        fb = st.get("feedback")
        if fb:
            # budgeter accuracy: how wrong the static estimates are
            # (median |log(est/actual)| over recorded nodes), what verdicts
            # the budgeter handed out, and how often a lookup found a
            # recorded actual to override with
            lookups = fb.get("lookups") or 0
            verdicts = {}
            for labels, v in self.registry.counter_series(
                    "nds_plan_budget_total").items():
                for k, val in labels:
                    if k == "verdict":
                        verdicts[val] = verdicts.get(val, 0) + int(v)
            st["budgeter_accuracy"] = {
                "err_median": fb.get("err_median"),
                "err_samples": fb.get("err_n") or 0,
                "feedback_hit_rate": (
                    round((fb.get("hits") or 0) / lookups, 4)
                    if lookups else None
                ),
                "feedback_mode": fb.get("mode"),
                "verdicts": verdicts,
            }
        hb = st.get("heartbeat_ts_ms")
        # epoch-minus-epoch on purpose: heartbeat `ts` is the event's epoch
        # stamp (possibly from another thread's clock read) — there is no
        # monotonic pair to subtract; a rare NTP step skews one snapshot's
        # AGE display, never a recorded duration
        # nds-lint: disable=perf-counter
        st["heartbeat_age_ms"] = (now_ms - hb) if hb else None
        # nds-lint: disable=perf-counter
        st["uptime_ms"] = now_ms - st["started_ts_ms"]
        provider = self._fleet_provider
        if provider is not None:
            # live router state (replica health, degraded capabilities,
            # fleet tenant in-flight) — merged outside _slock: the
            # provider takes the router's own lock
            try:
                live = provider()
            except Exception:
                live = None
            if isinstance(live, dict):
                fleet = st.setdefault("fleet", {
                    "requests": 0, "edge_rejected": 0, "retries": 0,
                    "tenants": {},
                })
                fleet.update(live)
        return st


#: kind -> bound-method handler (resolved once at import; record() does a
#: single dict lookup per event — the sink's whole hot path)
_HANDLERS = {
    "op_span": MetricsSink._h_op_span,
    "query_span": MetricsSink._h_query_span,
    "plan_cache": MetricsSink._h_plan_cache,
    "catalog_load": MetricsSink._h_catalog_load,
    "exec_cache": MetricsSink._h_exec_cache,
    "aot_cache": MetricsSink._h_aot_cache,
    "pipeline_span": MetricsSink._h_pipeline_span,
    "kernel_span": MetricsSink._h_kernel_span,
    "blocked_union": MetricsSink._h_blocked_union,
    "exchange": MetricsSink._h_exchange,
    "mesh_fallback": MetricsSink._h_mesh_fallback,
    "spill": MetricsSink._h_spill,
    "lake_commit": MetricsSink._h_lake_commit,
    "lake_vacuum": MetricsSink._h_lake_vacuum,
    "ingest_chunk": MetricsSink._h_ingest_chunk,
    "scan_prune": MetricsSink._h_scan_prune,
    "catalog_commit": MetricsSink._h_catalog_commit,
    "catalog_lease": MetricsSink._h_catalog_lease,
    "fault_injected": MetricsSink._h_fault_injected,
    "ladder_rung": MetricsSink._h_ladder_rung,
    "watchdog_fire": MetricsSink._h_watchdog_fire,
    "io_retry": MetricsSink._h_io_retry,
    "phase": MetricsSink._h_phase,
    "child_stream": MetricsSink._h_child_stream,
    "plan_verify": MetricsSink._h_plan_verify,
    "plan_budget": MetricsSink._h_plan_budget,
    "plan_feedback": MetricsSink._h_plan_feedback,
    "mem_watermark": MetricsSink._h_mem_watermark,
    "heartbeat": MetricsSink._h_heartbeat,
    "serve_request": MetricsSink._h_serve_request,
    "route_request": MetricsSink._h_route_request,
    "route_retry": MetricsSink._h_route_retry,
    "lock_contention": MetricsSink._h_lock_contention,
}

# every handled kind must be a real schema kind (drift breaks import, not
# a 3am scrape); kinds without a handler (trace_meta) are counted nowhere
assert set(_HANDLERS) <= set(EVENT_SCHEMA)


# ---------------------------------------------------------------------------
# process-wide singletons: one sink + one endpoint per process
# ---------------------------------------------------------------------------

_SHARED_LOCK = make_lock("obs/metrics.py:_SHARED_LOCK")
_SHARED = {}  # "sink": MetricsSink, "server": MetricsServer, "warned": bool


def shared_sink() -> MetricsSink:
    """The process-wide sink, created on first use (all sessions/streams
    of a process aggregate into one exposition, like executors reporting
    into one driver UI)."""
    with _SHARED_LOCK:
        sink = _SHARED.get("sink")
        if sink is None:
            sink = _SHARED["sink"] = MetricsSink()
        return sink


def active_server():
    """The running MetricsServer (read `.port` for an ephemeral bind), or
    None when the endpoint is off / failed to bind."""
    return _SHARED.get("server")


def maybe_serve(conf: dict | None = None):
    """The shared MetricsSink when live telemetry is configured
    (`engine.metrics_port` / NDS_METRICS_PORT), with the HTTP endpoint
    started on first call; None when disabled — the zero-cost default.

    A bind failure (port taken — e.g. a throughput child inheriting the
    parent's fixed port) warns once and returns the sink anyway: counters
    still aggregate, only this process's exposition is missing."""
    port = resolve_metrics_port(conf)
    if port is None:
        return None
    sink = shared_sink()
    with _SHARED_LOCK:
        if _SHARED.get("server") is None and not _SHARED.get("warned"):
            from .httpserv import MetricsServer

            try:
                _SHARED["server"] = MetricsServer(sink, port).start()
            except OSError as exc:
                _SHARED["warned"] = True
                print(
                    f"obs: metrics endpoint disabled "
                    f"(port {port}: {exc}); counters stay live in-process"
                )
    return sink


def reset_shared():
    """Stop the shared server and drop the shared sink (test isolation;
    production processes never call this — the endpoint lives as long as
    the process)."""
    with _SHARED_LOCK:
        server = _SHARED.pop("server", None)
        _SHARED.pop("sink", None)
        _SHARED.pop("warned", None)
    if server is not None:
        server.stop()
