"""The live-telemetry HTTP endpoint: /metrics, /statusz, /healthz — and,
in serve mode, the query-service routes on the SAME listener.

A stdlib `http.server` ThreadingHTTPServer on a daemon thread — no new
dependency, nothing to install on a fleet node. Started by
`obs.metrics.maybe_serve` when `engine.metrics_port` / NDS_METRICS_PORT
is set (off by default; 0 binds an ephemeral port — the CI e2e reads it
back from `MetricsServer.port`).

    GET /metrics   Prometheus text exposition of the registry
    GET /statusz   JSON run status: current phase, in-flight query with
                   elapsed/attempt/ladder, completed/failed counts, cache
                   hit rates, RSS + memory high-water, heartbeat age, a
                   `mesh` section (per-device HBM high-water + last
                   exchange skew/bytes) and per-tenant serve stats when
                   serve mode is attached
    GET /healthz   "ok" liveness; 503 "draining" once a serve-mode drain
                   begins, so load balancers stop routing BEFORE shutdown
    GET /debug/flight    the flight recorder's current bundle (last-N
                   events + plan/budget/ladder/memory/conf context) as
                   JSON; `?write=1` also persists it as a
                   failure-bundle-<trace_id>.json (obs/flight.py)
    GET /debug/jaxprof   on-demand jax.profiler status; POST with
                   {"action": "start"|"stop", "dir": ...} starts/stops a
                   profiler trace on the LIVE process (the "why is this
                   serve worker slow right now" tool)

Debug-route invariant (lint `debug-route-seam`): every /debug route
registers HERE, on the one process-wide listener — never on a second
listener, and serve-mode apps reach theirs through `attach_app` exactly
like the query routes.

Serve mode (`nds_tpu/serve/`) attaches an application via `attach_app`:
any route the built-ins above don't own is dispatched to
`app.handle_http(method, path, headers, body)` — POST /query, /stream,
/drain, /reload, GET /jobs/<id> all ride this one process-wide listener
instead of binding a second port. POST bodies are size-capped, and a
per-connection read timeout bounds what a slow (or slowloris) client can
hold: a stalled socket times out and closes, never wedging a worker.

The built-in handlers only READ sink state (every read path takes the
sink's own locks), so a scrape can never block or corrupt the run it
watches; the server thread is a daemon, so a finished benchmark process
never hangs on it."""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..engine.lockdebug import make_lock

#: largest accepted POST body (a query request is SQL text + a small JSON
#: envelope; anything bigger is a client bug or a flood)
MAX_BODY_BYTES = 8 << 20

#: on-demand jax.profiler state (one profiler per process — jax itself
#: enforces that); guarded by its lock because two /debug/jaxprof POSTs
#: may race on the threading server
_JAXPROF_LOCK = make_lock("obs/httpserv.py:_JAXPROF_LOCK")
_JAXPROF = {"dir": None, "started_ts_ms": None}


def _jaxprof_status() -> dict:
    with _JAXPROF_LOCK:
        return {
            "running": _JAXPROF["dir"] is not None,
            "dir": _JAXPROF["dir"],
            "started_ts_ms": _JAXPROF["started_ts_ms"],
        }


def _jaxprof_action(payload: dict) -> tuple:
    """(status_code, body_dict) for a /debug/jaxprof POST."""
    import time

    action = str(payload.get("action") or "").lower()
    if action not in ("start", "stop"):
        return 400, {"error": "action must be 'start' or 'stop'"}
    try:
        import jax
    except Exception as exc:  # pragma: no cover - jax is a hard dep
        return 500, {"error": f"jax unavailable: {type(exc).__name__}"}
    with _JAXPROF_LOCK:
        if action == "start":
            if _JAXPROF["dir"] is not None:
                return 409, {
                    "error": "profiler already running",
                    "dir": _JAXPROF["dir"],
                }
            from .flight import resolve_flight_dir

            d = payload.get("dir") or os.path.join(
                resolve_flight_dir(), f"jaxprof-{int(time.time())}"
            )
            try:
                jax.profiler.start_trace(str(d))
            except Exception as exc:
                return 500, {"error": f"start_trace: {exc}"}
            _JAXPROF["dir"] = str(d)
            _JAXPROF["started_ts_ms"] = int(time.time() * 1000)
            return 200, {"running": True, "dir": str(d)}
        if _JAXPROF["dir"] is None:
            return 409, {"error": "profiler not running"}
        d = _JAXPROF["dir"]
        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            return 500, {"error": f"stop_trace: {exc}"}
        _JAXPROF["dir"] = None
        _JAXPROF["started_ts_ms"] = None
        return 200, {"running": False, "dir": d}


class _Handler(BaseHTTPRequestHandler):
    server_version = "nds-tpu-metrics"
    # slow-client guard: BaseHTTPRequestHandler applies this as the
    # connection's socket timeout, so a client that stops sending (or
    # never sends) its request gets its connection closed instead of
    # holding a handler thread forever (the slowloris scenario)
    timeout = float(os.environ.get("NDS_SERVE_CLIENT_TIMEOUT_S", "60"))

    def _reply(self, code, body, ctype, headers=()):
        data = body.encode("utf-8") if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _dispatch_app(self, method, path, body):
        """Route a non-built-in path to the attached serve app (if any).
        Returns True when the app owned the route."""
        app = getattr(self.server, "app", None)
        if app is None:
            return False
        headers = {k.lower(): v for k, v in self.headers.items()}
        result = app.handle_http(method, path, headers, body)
        if result is None:
            return False
        status, ctype, payload, extra = result
        self._reply(status, payload, ctype, extra)
        return True

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        sink = self.server.sink
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(
                    200, sink.registry.render(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/statusz":
                self._reply(
                    200, json.dumps(sink.status_snapshot(), default=str),
                    "application/json",
                )
            elif path == "/healthz":
                app = getattr(self.server, "app", None)
                if app is not None and getattr(app, "draining", False):
                    # the load-balancer signal: stop routing here — the
                    # process is still alive (200s keep flowing on
                    # /metrics) but it is on its way out
                    self._reply(
                        503, "draining\n", "text/plain; charset=utf-8",
                        (("Retry-After", "5"),),
                    )
                else:
                    self._reply(200, "ok\n", "text/plain; charset=utf-8")
            elif path == "/debug/flight":
                self._debug_flight()
            elif path == "/debug/jaxprof":
                self._reply(
                    200, json.dumps(_jaxprof_status()), "application/json"
                )
            elif not self._dispatch_app("GET", path, None):
                self._reply(404, "not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply: its problem, not the run's
        except Exception as exc:  # app bug: a JSON 500, not a socket reset
            self._internal_error(exc)

    def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        path = self.path.split("?", 1)[0]
        try:
            try:
                # clamp below zero: a negative Content-Length would turn
                # rfile.read(length) into read-to-EOF, voiding the cap
                length = max(int(self.headers.get("Content-Length") or 0), 0)
            except ValueError:
                length = 0
            if length > MAX_BODY_BYTES:
                self._reply(
                    413, "request body too large\n",
                    "text/plain; charset=utf-8",
                )
                return
            body = self.rfile.read(length) if length else b""
            if path == "/debug/jaxprof":
                try:
                    payload = json.loads(body.decode("utf-8")) if body else {}
                except (ValueError, UnicodeDecodeError) as exc:
                    self._reply(
                        400, json.dumps({"error": str(exc)}),
                        "application/json",
                    )
                    return
                code, obj = _jaxprof_action(
                    payload if isinstance(payload, dict) else {}
                )
                self._reply(code, json.dumps(obj), "application/json")
                return
            try:
                handled = self._dispatch_app("POST", path, body)
            except ValueError as exc:  # malformed JSON body
                self._reply(
                    400, json.dumps({"error": str(exc)}), "application/json"
                )
                return
            if not handled:
                self._reply(404, "not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            # mid-query disconnect: the engine work (if any) completes on
            # its worker; only this connection's reply is lost
            pass
        except Exception as exc:  # app bug: a JSON 500, not a socket reset
            self._internal_error(exc)

    def _debug_flight(self):
        """GET /debug/flight: the current flight-recorder bundle, built on
        demand from the live ring. `?write=1` also persists it (the
        "grab me a black box from the live service" verb)."""
        from . import flight as obs_flight

        rec = obs_flight.recorder()
        if rec is None:
            self._reply(
                503,
                json.dumps({"error": "flight recorder disabled "
                            "(NDS_FLIGHT_RECORDER=off)"}),
                "application/json",
            )
            return
        from urllib.parse import parse_qs

        query = self.path.split("?", 1)
        params = parse_qs(query[1]) if len(query) > 1 else {}
        bundle = rec.bundle("on_demand")
        if params.get("write", ["0"])[-1] == "1":
            bundle["written"] = rec.flush("on_demand")
        self._reply(200, json.dumps(bundle, default=str), "application/json")

    def _internal_error(self, exc):
        """An exception escaping the attached app must still answer the
        client (otherwise the connection just resets with no status
        line); the body carries the exception TYPE only — messages can
        embed paths/SQL a multi-tenant endpoint must not leak."""
        try:
            self._reply(
                500,
                json.dumps({"error": f"internal: {type(exc).__name__}"}),
                "application/json",
            )
        except OSError:
            pass  # client already gone

    def log_message(self, format, *args):  # noqa: A002 (stdlib signature)
        pass  # a scrape every few seconds must not spam the bench stdout


class MetricsServer:
    """Daemon-thread HTTP server over one MetricsSink.

    `port=0` binds ephemeral; the resolved port is `self.port`. Bind host
    defaults to all interfaces (fleet scrapers live off-box) —
    NDS_METRICS_HOST overrides (e.g. 127.0.0.1 on a shared dev machine)."""

    def __init__(self, sink, port: int = 0, host: str | None = None):
        if host is None:
            host = os.environ.get("NDS_METRICS_HOST", "0.0.0.0")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.sink = sink
        self._httpd.app = None
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    def attach_app(self, app):
        """Attach a serve-mode application: routes the built-in telemetry
        paths don't own dispatch to `app.handle_http`, and /healthz reads
        `app.draining`. One listener, one port, the whole surface."""
        self._httpd.app = app

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="nds-obs-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
