"""The live-telemetry HTTP endpoint: /metrics, /statusz, /healthz.

A stdlib `http.server` ThreadingHTTPServer on a daemon thread — no new
dependency, nothing to install on a fleet node. Started by
`obs.metrics.maybe_serve` when `engine.metrics_port` / NDS_METRICS_PORT
is set (off by default; 0 binds an ephemeral port — the CI e2e reads it
back from `MetricsServer.port`).

    GET /metrics   Prometheus text exposition of the registry
    GET /statusz   JSON run status: current phase, in-flight query with
                   elapsed/attempt/ladder, completed/failed counts, cache
                   hit rates, RSS + memory high-water, heartbeat age
    GET /healthz   "ok" (liveness only; /statusz is the readiness story)

The handler only READS sink state (every read path takes the sink's own
locks), so a scrape can never block or corrupt the run it watches; the
server thread is a daemon, so a finished benchmark process never hangs
on it."""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Handler(BaseHTTPRequestHandler):
    server_version = "nds-tpu-metrics"

    def _reply(self, code, body, ctype):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        sink = self.server.sink
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(
                    200, sink.registry.render(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/statusz":
                self._reply(
                    200, json.dumps(sink.status_snapshot(), default=str),
                    "application/json",
                )
            elif path == "/healthz":
                self._reply(200, "ok\n", "text/plain; charset=utf-8")
            else:
                self._reply(404, "not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply: its problem, not the run's

    def log_message(self, format, *args):  # noqa: A002 (stdlib signature)
        pass  # a scrape every few seconds must not spam the bench stdout


class MetricsServer:
    """Daemon-thread HTTP server over one MetricsSink.

    `port=0` binds ephemeral; the resolved port is `self.port`. Bind host
    defaults to all interfaces (fleet scrapers live off-box) —
    NDS_METRICS_HOST overrides (e.g. 127.0.0.1 on a shared dev machine)."""

    def __init__(self, sink, port: int = 0, host: str | None = None):
        if host is None:
            host = os.environ.get("NDS_METRICS_HOST", "0.0.0.0")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.sink = sink
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="nds-obs-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
