"""The live-telemetry HTTP endpoint: /metrics, /statusz, /healthz — and,
in serve mode, the query-service routes on the SAME listener.

A stdlib `http.server` ThreadingHTTPServer on a daemon thread — no new
dependency, nothing to install on a fleet node. Started by
`obs.metrics.maybe_serve` when `engine.metrics_port` / NDS_METRICS_PORT
is set (off by default; 0 binds an ephemeral port — the CI e2e reads it
back from `MetricsServer.port`).

    GET /metrics   Prometheus text exposition of the registry
    GET /statusz   JSON run status: current phase, in-flight query with
                   elapsed/attempt/ladder, completed/failed counts, cache
                   hit rates, RSS + memory high-water, heartbeat age (and
                   per-tenant serve stats when serve mode is attached)
    GET /healthz   "ok" liveness; 503 "draining" once a serve-mode drain
                   begins, so load balancers stop routing BEFORE shutdown

Serve mode (`nds_tpu/serve/`) attaches an application via `attach_app`:
any route the built-ins above don't own is dispatched to
`app.handle_http(method, path, headers, body)` — POST /query, /stream,
/drain, /reload, GET /jobs/<id> all ride this one process-wide listener
instead of binding a second port. POST bodies are size-capped, and a
per-connection read timeout bounds what a slow (or slowloris) client can
hold: a stalled socket times out and closes, never wedging a worker.

The built-in handlers only READ sink state (every read path takes the
sink's own locks), so a scrape can never block or corrupt the run it
watches; the server thread is a daemon, so a finished benchmark process
never hangs on it."""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

#: largest accepted POST body (a query request is SQL text + a small JSON
#: envelope; anything bigger is a client bug or a flood)
MAX_BODY_BYTES = 8 << 20


class _Handler(BaseHTTPRequestHandler):
    server_version = "nds-tpu-metrics"
    # slow-client guard: BaseHTTPRequestHandler applies this as the
    # connection's socket timeout, so a client that stops sending (or
    # never sends) its request gets its connection closed instead of
    # holding a handler thread forever (the slowloris scenario)
    timeout = float(os.environ.get("NDS_SERVE_CLIENT_TIMEOUT_S", "60"))

    def _reply(self, code, body, ctype, headers=()):
        data = body.encode("utf-8") if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _dispatch_app(self, method, path, body):
        """Route a non-built-in path to the attached serve app (if any).
        Returns True when the app owned the route."""
        app = getattr(self.server, "app", None)
        if app is None:
            return False
        headers = {k.lower(): v for k, v in self.headers.items()}
        result = app.handle_http(method, path, headers, body)
        if result is None:
            return False
        status, ctype, payload, extra = result
        self._reply(status, payload, ctype, extra)
        return True

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        sink = self.server.sink
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(
                    200, sink.registry.render(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/statusz":
                self._reply(
                    200, json.dumps(sink.status_snapshot(), default=str),
                    "application/json",
                )
            elif path == "/healthz":
                app = getattr(self.server, "app", None)
                if app is not None and getattr(app, "draining", False):
                    # the load-balancer signal: stop routing here — the
                    # process is still alive (200s keep flowing on
                    # /metrics) but it is on its way out
                    self._reply(
                        503, "draining\n", "text/plain; charset=utf-8",
                        (("Retry-After", "5"),),
                    )
                else:
                    self._reply(200, "ok\n", "text/plain; charset=utf-8")
            elif not self._dispatch_app("GET", path, None):
                self._reply(404, "not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply: its problem, not the run's
        except Exception as exc:  # app bug: a JSON 500, not a socket reset
            self._internal_error(exc)

    def do_POST(self):  # noqa: N802 (BaseHTTPRequestHandler contract)
        path = self.path.split("?", 1)[0]
        try:
            try:
                # clamp below zero: a negative Content-Length would turn
                # rfile.read(length) into read-to-EOF, voiding the cap
                length = max(int(self.headers.get("Content-Length") or 0), 0)
            except ValueError:
                length = 0
            if length > MAX_BODY_BYTES:
                self._reply(
                    413, "request body too large\n",
                    "text/plain; charset=utf-8",
                )
                return
            body = self.rfile.read(length) if length else b""
            try:
                handled = self._dispatch_app("POST", path, body)
            except ValueError as exc:  # malformed JSON body
                self._reply(
                    400, json.dumps({"error": str(exc)}), "application/json"
                )
                return
            if not handled:
                self._reply(404, "not found\n", "text/plain; charset=utf-8")
        except BrokenPipeError:
            # mid-query disconnect: the engine work (if any) completes on
            # its worker; only this connection's reply is lost
            pass
        except Exception as exc:  # app bug: a JSON 500, not a socket reset
            self._internal_error(exc)

    def _internal_error(self, exc):
        """An exception escaping the attached app must still answer the
        client (otherwise the connection just resets with no status
        line); the body carries the exception TYPE only — messages can
        embed paths/SQL a multi-tenant endpoint must not leak."""
        try:
            self._reply(
                500,
                json.dumps({"error": f"internal: {type(exc).__name__}"}),
                "application/json",
            )
        except OSError:
            pass  # client already gone

    def log_message(self, format, *args):  # noqa: A002 (stdlib signature)
        pass  # a scrape every few seconds must not spam the bench stdout


class MetricsServer:
    """Daemon-thread HTTP server over one MetricsSink.

    `port=0` binds ephemeral; the resolved port is `self.port`. Bind host
    defaults to all interfaces (fleet scrapers live off-box) —
    NDS_METRICS_HOST overrides (e.g. 127.0.0.1 on a shared dev machine)."""

    def __init__(self, sink, port: int = 0, host: str | None = None):
        if host is None:
            host = os.environ.get("NDS_METRICS_HOST", "0.0.0.0")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.sink = sink
        self._httpd.app = None
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = None

    def attach_app(self, app):
        """Attach a serve-mode application: routes the built-in telemetry
        paths don't own dispatch to `app.handle_http`, and /healthz reads
        `app.draining`. One listener, one port, the whole surface."""
        self._httpd.app = app

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="nds-obs-metrics-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
