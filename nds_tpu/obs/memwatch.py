"""Per-query memory high-water sampling for the observability stream.

Preferred source: jax device memory stats (`Device.memory_stats()["bytes_in_use"]`,
available on real accelerator backends) summed over local devices. Fallback:
process RSS from /proc/self/statm (the CPU backend allocates query
intermediates in host memory, so RSS is the honest proxy there — and it is
also the signal the ROADMAP's host-OOM pre-emption item will watch).

The sampler is a daemon thread started only while a traced query runs
(BenchReport gates it on the session tracer), so with tracing off it costs
nothing. Interval knob: NDS_TRACE_MEM_INTERVAL_MS (default 50).

Heartbeats: because this thread is the one part of a query that keeps
running while the query itself may be wedged, it doubles as the liveness
beacon — with a tracer attached it emits a `heartbeat` event (query,
elapsed_ms, rss_bytes) every NDS_HEARTBEAT_INTERVAL_MS (default 1000),
so a hang is visible live (/statusz heartbeat age keeps ticking while
in-flight elapsed grows) and classifiable post-hoc from the log tail.
"""

from __future__ import annotations

import os
import threading
import time

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def device_bytes_per_device():
    """Per-device `bytes_in_use` over local jax devices as a list (index
    = local device ordinal), or None when the backend exposes no memory
    stats (CPU), or jax isn't importable here. Kept PER DEVICE on
    purpose: the /statusz `mesh` section and failure bundles want the
    straggler device visible, not one aggregated max."""
    try:
        import jax

        out = []
        seen = False
        for d in jax.local_devices():
            stats = d.memory_stats()
            v = stats.get("bytes_in_use") if stats else None
            out.append(int(v) if v is not None else 0)
            if v is not None:
                seen = True
        return out if seen else None
    except Exception:
        return None


def device_bytes_in_use():
    """Total bytes_in_use over local jax devices, or None when the backend
    exposes no memory stats (CPU), or jax isn't importable here."""
    per = device_bytes_per_device()
    return sum(per) if per is not None else None


def rss_bytes():
    """Resident set size from /proc/self/statm, or None off-Linux."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return None


class MemorySampler:
    """Background high-water sampler: max over periodic samples of the best
    available memory signal. Use as a context manager; read `.peak_bytes`
    (int | None) and `.source` ("device" | "rss" | None) after exit.

    Host-OOM pre-emption: with `watermark_bytes` set, the sampler fires
    `on_watermark(sample_bytes)` ONCE from its thread the first time the
    process RSS crosses the watermark — the ladder-before-the-allocator
    hook report.py uses to shrink the blocked-union window mid-query
    (ROADMAP carry-forward: pre-empt via RSS watermarks before the
    allocator fails). The watermark always watches RSS, independent of
    which signal feeds `peak_bytes`: host allocation death is a host-side
    phenomenon even when device stats are the better high-water source."""

    def __init__(self, interval_s: float | None = None,
                 watermark_bytes: int | None = None, on_watermark=None,
                 tracer=None, query=None, heartbeat_s: float | None = None,
                 on_heartbeat=None):
        if interval_s is None:
            interval_s = (
                float(os.environ.get("NDS_TRACE_MEM_INTERVAL_MS", "50")) / 1000
            )
        self.interval_s = max(interval_s, 0.001)
        # single-writer discipline instead of a lock: every field below
        # is mutated only by the sampler thread (_sample) or by the
        # owner before start / after join (__enter__/__exit__), and the
        # owner reads peaks only after __exit__'s join
        self.peak_bytes = None  # nds-guarded-by: none
        #: per-device high-water (list, device-source runs only): the
        #: straggler-visible half of the peak — query_span carries it as
        #: `mem_hw_per_device` and /statusz's mesh section max-merges it
        self.peak_per_device = None  # nds-guarded-by: none
        self.source = None
        self.watermark_bytes = watermark_bytes or None
        self.on_watermark = on_watermark
        self.watermark_fired = False  # nds-guarded-by: none
        # heartbeat beacon (module docstring): emitted through `tracer`
        # (passed explicitly — thread-locals don't reach this thread)
        # at most every `heartbeat_s`; tracer None disables it
        self.tracer = tracer
        self.query = query
        # per-beat liveness work beyond the beacon (e.g. report.py renews
        # the session's lakehouse reader leases here, so a statement
        # outliving the lease TTL keeps its snapshot vacuum-safe); runs
        # on the sampler thread even when no tracer is attached
        self.on_heartbeat = on_heartbeat
        if heartbeat_s is None:
            heartbeat_s = (
                float(os.environ.get("NDS_HEARTBEAT_INTERVAL_MS", "1000"))
                / 1000
            )
        self.heartbeat_s = max(heartbeat_s, 0.0)
        self._last_hb = None  # nds-guarded-by: none
        self._t0 = None  # nds-guarded-by: none
        self._stop = threading.Event()
        self._thread = None
        # probe once up front so source selection is stable for the run
        if device_bytes_in_use() is not None:
            self._read, self.source = device_bytes_in_use, "device"
        elif rss_bytes() is not None:
            self._read, self.source = rss_bytes, "rss"
        else:
            self._read = None

    def _sample(self):
        per_dev = None
        if self.source == "device":
            per_dev = device_bytes_per_device()
            v = sum(per_dev) if per_dev is not None else None
            if per_dev is not None:
                if self.peak_per_device is None:
                    self.peak_per_device = list(per_dev)
                else:
                    for i, b in enumerate(per_dev):
                        if i < len(self.peak_per_device):
                            if b > self.peak_per_device[i]:
                                self.peak_per_device[i] = b
                        else:
                            self.peak_per_device.append(b)
        else:
            v = self._read() if self._read is not None else None
        if v is not None and (self.peak_bytes is None or v > self.peak_bytes):
            self.peak_bytes = v
        if (
            self.watermark_bytes
            and not self.watermark_fired
            and self.on_watermark is not None
        ):
            r = v if self.source == "rss" else rss_bytes()
            if r is not None and r >= self.watermark_bytes:
                self.watermark_fired = True
                try:
                    self.on_watermark(r)
                except Exception:
                    pass  # pre-emption must never take the query down
        if (
            self.heartbeat_s
            and (self.tracer is not None or self.on_heartbeat is not None)
        ):
            now = time.monotonic()
            if self._last_hb is None or now - self._last_hb >= self.heartbeat_s:
                self._last_hb = now
                if self.on_heartbeat is not None:
                    try:
                        self.on_heartbeat()
                    except Exception:
                        pass  # beat work must never take the query down
                if self.tracer is None:
                    return
                r = v if self.source == "rss" else rss_bytes()
                try:
                    self.tracer.emit(
                        "heartbeat",
                        query=self.query,
                        elapsed_ms=round((now - self._t0) * 1000, 1),
                        rss_bytes=r,
                        # per-device HBM rides the beacon so the live
                        # /statusz mesh section tracks each device's
                        # high-water, not one aggregated max
                        **({"dev_bytes": list(per_dev)}
                           if per_dev is not None else {}),
                    )
                except Exception:
                    pass  # the beacon must never take the query down

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self._sample()

    def __enter__(self):
        self._t0 = time.monotonic()
        # the thread also runs with no readable memory signal when a
        # tracer wants heartbeats (or beat work is registered): the
        # beacon is about liveness, not bytes
        if (
            self._read is not None
            or self.tracer is not None
            or self.on_heartbeat is not None
        ):
            self._sample()
            self._thread = threading.Thread(
                target=self._loop, name="nds-obs-memwatch", daemon=True
            )
            self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._sample()  # final reading: catch an end-of-query peak
        return False
