"""Critical-path reconstruction: attribute per-query wall time to CAUSES.

The operator profiler (obs/reader.py) answers "which operator is hot";
this module answers the diagnosis question ROADMAP items 2/3 stall on:
*where does the wall clock actually go* — compile/dispatch inside plan
nodes, exchange waits (and how much of them is skew), spill IO, catalog
loads, degradation-ladder retries, watchdog-abandoned hangs, or
driver-side planning/host work. It reconstructs each query's dependency
chain from the events the engine already emits (`op_span` exec_id/seq/
depth rebuild the plan tree; `exchange`/`spill`/`catalog_load` carry
measured durations; `ladder_rung` carries the failed attempt's wall) and
rolls the evidence into a per-query cause table plus a mesh summary that
names the straggler device from per-device exchange row counts.

Attribution semantics (each bucket is wall-clock, disjoint by
construction):

    exchange-wait   measured `exchange` dur_ms (the collective, both
                    all_to_all passes + retries); `exchange-skew` is the
                    imbalance share of that wait, dur * (1 - 1/skew) —
                    what a perfectly balanced exchange would give back
    spill-io        measured `spill` dur_ms (partition + segment IO +
                    per-partition execution of the out-of-core op)
    catalog-load    measured `catalog_load` dur_ms
    execute         remaining root op_span inclusive time: plan-node
                    device compute + dispatch + any jit compile paid
                    inside the node (first-touch pipelines)
    ladder-retry    failed attempts' wall (`ladder_rung.attempt_ms`)
    backoff-wait    deliberate sleeps between rungs (delay_s)
    hung-wait       a watchdog-abandoned attempt's budget
    ingest-decode   measured `ingest_chunk` decode_ms (Arrow decode of
                    the chunk file in a transcode worker)
    ingest-commit-wait  measured `ingest_chunk` commit_ms (staging +
                    the OCC commit, including rebase waits behind
                    concurrent writers)
    prune-planning  measured `scan_prune` dur_ms (zone-map evaluation
                    at plan time — carved out of what used to be the
                    plan-host residual)
    router-queue    `route_request` queue_ms: router-edge admission
                    (verdict cache lookup / /plan probe + replica pick)
                    before the first forward left the router
    router-forward  router-side upstream wire time NOT explained by
                    replica-side execution, max(forward_ms - replica
                    wall, 0) — failover retries, backoff sleeps, and
                    transfer. When a trace has route events but no
                    replica query_span (the replica died, or only the
                    router's log is at hand), the whole forward time
                    lands here and the route dur_ms IS the wall
    plan-host       the driver residual: parse/bind/rewrite/budget,
                    host-side result materialization, report overhead —
                    the same "driver time" bucket the reference's
                    profiling tool derives for non-stage wall. Counted
                    as ATTRIBUTED only while it stays a minority share
                    (<= MAX_RESIDUAL_FRAC of wall); a larger residual
                    means span evidence is missing, and the honest
                    answer is `unattributed` — the CI gate's >= 90%
                    assertion then fails instead of laundering the gap.
"""

from __future__ import annotations

#: residual share of wall beyond which plan-host stops counting as
#: attributed (evidence-coverage collapse, not driver work)
MAX_RESIDUAL_FRAC = 0.5

#: cause names in render order
CAUSE_ORDER = (
    "execute", "exchange-wait", "spill-io", "catalog-load", "ladder-retry",
    "backoff-wait", "hung-wait", "ingest-decode", "ingest-commit-wait",
    "prune-planning", "router-queue", "router-forward", "plan-host",
)


def _group_query_events(events) -> dict:
    """{query name: [events]} for the kinds the reconstruction reads."""
    out = {}
    for ev in events:
        kind = ev.get("kind")
        if kind in ("op_span", "query_span", "exchange", "spill",
                    "catalog_load", "ladder_rung", "watchdog_fire",
                    "kernel_span", "ingest_chunk", "scan_prune",
                    "route_request"):
            q = ev.get("query") or "<unscoped>"
            out.setdefault(q, []).append(ev)
    return out


def _op_tree_chain(spans) -> list:
    """The critical chain of one query's op spans: rebuild the plan tree
    from (exec_id, seq, depth) post-order, then walk root -> heaviest
    child. Returns [{"node", "dur_ms", "depth"}...] root-first for the
    LAST executed root (the attempt that produced the result)."""
    by_exec = {}
    for ev in spans:
        by_exec.setdefault(ev.get("exec_id"), []).append(ev)
    best = None
    for evs in by_exec.values():
        evs.sort(key=lambda e: e.get("seq", 0))
        pending = {}  # depth -> [(span, children)]
        roots = []
        for ev in evs:
            d = ev.get("depth", 0)
            children = pending.pop(d + 1, [])
            rec = (ev, children)
            if d == 0:
                roots.append(rec)
            else:
                pending.setdefault(d, []).append(rec)
        if roots:
            best = roots[-1]
    if best is None:
        return []
    chain = []
    node = best
    while node is not None:
        ev, children = node
        chain.append({
            "node": ev.get("node"),
            "dur_ms": float(ev.get("dur_ms") or 0.0),
            "depth": ev.get("depth", 0),
        })
        node = max(
            children, key=lambda c: float(c[0].get("dur_ms") or 0.0),
            default=None,
        )
    return chain


def _skew_ms(ev) -> float:
    """The imbalance share of one exchange's wait: the time a perfectly
    balanced partition map would have given back, dur * (1 - 1/skew)."""
    try:
        dur = float(ev.get("dur_ms") or 0.0)
        skew = float(ev.get("skew") or 1.0)
    except (TypeError, ValueError):
        return 0.0
    if dur <= 0 or skew <= 1.0:
        return 0.0
    return dur * (1.0 - 1.0 / skew)


def critical_path(events) -> dict:
    """Per-query cause attribution + mesh straggler summary over one or
    more streams' events. Returns::

        {"queries": {name: {"wall_ms", "runs", "status", "causes": {...},
                            "attributed_ms", "attributed_frac",
                            "kernel_ms", "chain": [...],
                            "exchange": {...} | None}},
         "mesh": {...} | None}
    """
    queries = {}
    # mesh roll-up across queries: per-device received rows + skew cost
    mesh_rows = []
    mesh_exchange_ms = 0.0
    mesh_skew_ms = 0.0
    mesh_ops = 0
    for q, evs in sorted(_group_query_events(events).items()):
        wall = 0.0
        runs = 0
        status = None
        spans = []
        exch_ms = skew_ms = spill_ms = cat_ms = 0.0
        ladder_ms = backoff_ms = hung_ms = kernel_ms = 0.0
        decode_ms = commit_wait_ms = prune_ms = 0.0
        route_n = 0
        route_dur_ms = route_queue_ms = route_forward_ms = 0.0
        route_status = None
        exch_rows = None  # per-device received rows, element-wise summed
        exch_worst = None  # the highest-skew exchange event
        for ev in evs:
            kind = ev["kind"]
            if kind == "query_span":
                wall += float(ev.get("dur_ms") or 0.0)
                runs += 1
                if status != "Failed":
                    status = ev.get("status")
            elif kind == "op_span":
                spans.append(ev)
            elif kind == "exchange":
                mesh_ops += 1
                d = float(ev.get("dur_ms") or 0.0)
                exch_ms += d
                s = _skew_ms(ev)
                skew_ms += s
                mesh_exchange_ms += d
                mesh_skew_ms += s
                per = ev.get("per_device")
                if isinstance(per, list) and per:
                    if exch_rows is None:
                        exch_rows = [0] * len(per)
                    for i, r in enumerate(per):
                        if i >= len(exch_rows):
                            exch_rows.append(0)
                        exch_rows[i] += int(r or 0)
                    while len(mesh_rows) < len(per):
                        mesh_rows.append(0)
                    for i, r in enumerate(per):
                        mesh_rows[i] += int(r or 0)
                try:
                    sk = float(ev.get("skew") or 1.0)
                except (TypeError, ValueError):
                    sk = 1.0
                if exch_worst is None or sk > exch_worst[0]:
                    exch_worst = (sk, ev)
            elif kind == "spill":
                spill_ms += float(ev.get("dur_ms") or 0.0)
            elif kind == "catalog_load":
                cat_ms += float(ev.get("dur_ms") or 0.0)
            elif kind == "ladder_rung":
                ladder_ms += float(ev.get("attempt_ms") or 0.0)
                backoff_ms += float(ev.get("delay_s") or 0.0) * 1000.0
            elif kind == "watchdog_fire":
                hung_ms += float(ev.get("budget_s") or 0.0) * 1000.0
            elif kind == "kernel_span":
                kernel_ms += float(ev.get("dur_ms") or 0.0)
            elif kind == "ingest_chunk":
                decode_ms += float(ev.get("decode_ms") or 0.0)
                commit_wait_ms += float(ev.get("commit_ms") or 0.0)
            elif kind == "scan_prune":
                prune_ms += float(ev.get("dur_ms") or 0.0)
            elif kind == "route_request":
                route_n += 1
                route_dur_ms += float(ev.get("dur_ms") or 0.0)
                route_queue_ms += float(ev.get("queue_ms") or 0.0)
                route_forward_ms += float(ev.get("forward_ms") or 0.0)
                if route_status != "Failed":
                    route_status = (
                        "Completed" if ev.get("status") == "completed"
                        else "Failed"
                    )
        if route_n:
            # the router hop wraps replica-side execution: the router's
            # end-to-end dur is the fleet wall (>= the replica's
            # query_span wall when both logs fold into one trace), and
            # router-forward is only the upstream time the replica wall
            # does NOT explain (failover retries, backoff, transfer) so
            # the buckets stay disjoint
            replica_wall = wall
            wall = max(wall, route_dur_ms)
            runs = runs or route_n
            status = status or route_status
            route_forward_ms = max(route_forward_ms - replica_wall, 0.0)
        root_incl = sum(
            float(e.get("dur_ms") or 0.0)
            for e in spans
            if e.get("depth", 0) == 0
        )
        # measured sub-causes live INSIDE plan-node execution; `execute`
        # is what remains of the root inclusive time after carving them
        # out (floored: an exchange that outlived its op span under
        # clock jitter must not go negative)
        execute = max(root_incl - exch_ms - spill_ms - cat_ms, 0.0)
        # hung-wait is capped at what the OTHER measured causes leave of
        # the wall (the abandoned attempt's partial spans may overlap the
        # budget; counting both would over-attribute)
        others = (
            execute + exch_ms + spill_ms + cat_ms + ladder_ms + backoff_ms
            + decode_ms + commit_wait_ms + prune_ms
            + route_queue_ms + route_forward_ms
        )
        causes = {
            "execute": round(execute, 3),
            "exchange-wait": round(exch_ms, 3),
            "spill-io": round(spill_ms, 3),
            "catalog-load": round(cat_ms, 3),
            "ladder-retry": round(ladder_ms, 3),
            "backoff-wait": round(backoff_ms, 3),
            "hung-wait": round(min(hung_ms, max(wall - others, 0.0)), 3)
            if hung_ms else 0.0,
            "ingest-decode": round(decode_ms, 3),
            "ingest-commit-wait": round(commit_wait_ms, 3),
            "prune-planning": round(prune_ms, 3),
            "router-queue": round(route_queue_ms, 3),
            "router-forward": round(route_forward_ms, 3),
        }
        measured = sum(causes.values())
        residual = wall - measured
        if 0.0 <= residual <= wall * MAX_RESIDUAL_FRAC:
            causes["plan-host"] = round(residual, 3)
            unattributed = 0.0
        else:
            # negative residual (cross-thread clock jitter / evidence
            # overlap) or a majority residual (missing spans): report the
            # gap honestly instead of inventing a cause for it
            causes["plan-host"] = 0.0
            unattributed = max(residual, 0.0)
        attributed = min(sum(causes.values()), wall) if wall else 0.0
        qrec = {
            "wall_ms": round(wall, 3),
            "runs": runs,
            "status": status,
            "causes": causes,
            "attributed_ms": round(attributed, 3),
            "attributed_frac": round(attributed / wall, 4) if wall else None,
            "unattributed_ms": round(unattributed, 3),
            "kernel_ms": round(kernel_ms, 3),  # overlaps execute: info only
            "chain": _op_tree_chain(spans),
        }
        if exch_worst is not None:
            sk, ev = exch_worst
            straggler = None
            if isinstance(exch_rows, list) and exch_rows and max(exch_rows):
                straggler = int(max(
                    range(len(exch_rows)), key=lambda i: exch_rows[i]
                ))
            qrec["exchange"] = {
                "ops": sum(1 for e in evs if e["kind"] == "exchange"),
                "wait_ms": round(exch_ms, 3),
                "skew_ms": round(skew_ms, 3),
                "max_skew": sk,
                "straggler_device": straggler,
                "per_device_rows": exch_rows,
            }
        else:
            qrec["exchange"] = None
        queries[q] = qrec
    mesh = None
    if mesh_ops:
        straggler = None
        if mesh_rows and max(mesh_rows):
            straggler = int(max(
                range(len(mesh_rows)), key=lambda i: mesh_rows[i]
            ))
        mesh = {
            "exchange_ops": mesh_ops,
            "exchange_ms": round(mesh_exchange_ms, 3),
            "skew_ms": round(mesh_skew_ms, 3),
            "skew_share": round(mesh_skew_ms / mesh_exchange_ms, 4)
            if mesh_exchange_ms else None,
            "straggler_device": straggler,
            "per_device_rows": mesh_rows or None,
        }
    return {"queries": queries, "mesh": mesh}


def min_attributed_frac(cp: dict):
    """The worst per-query attribution share of a `critical_path` result
    (None when it profiled no timed queries) — the CI diagnosis gate's
    >= 0.9 assertion reads this."""
    fracs = [
        q["attributed_frac"]
        for q in cp["queries"].values()
        if q["attributed_frac"] is not None
    ]
    return min(fracs) if fracs else None


def render(cp: dict, out=None) -> None:
    """Human rendering of a `critical_path` result (the profiler CLI's
    --critical-path text mode)."""
    import sys

    out = out or sys.stdout

    def p(line=""):
        print(line, file=out)

    queries = cp["queries"]
    p(f"== critical path: {len(queries)} queries")
    for q in sorted(queries):
        rec = queries[q]
        frac = rec["attributed_frac"]
        frac_s = "-" if frac is None else f"{frac:.0%}"
        status = rec.get("status") or "?"
        p(f"\n-- {q}: wall {rec['wall_ms']:,.1f} ms  {status}  "
          f"(attributed {frac_s})")
        for cause in CAUSE_ORDER:
            ms = rec["causes"].get(cause, 0.0)
            if ms <= 0:
                continue
            share = ms / rec["wall_ms"] if rec["wall_ms"] else 0.0
            p(f"   {cause:<14}{ms:>12,.1f} ms  {share:>6.1%}")
        if rec.get("unattributed_ms"):
            p(f"   {'unattributed':<14}{rec['unattributed_ms']:>12,.1f} ms")
        if rec["chain"]:
            hops = " -> ".join(
                f"{c['node']} {c['dur_ms']:,.0f}ms" for c in rec["chain"][:6]
            )
            p(f"   chain: {hops}")
        ex = rec.get("exchange")
        if ex is not None and ex["wait_ms"]:
            dev = (
                f"device {ex['straggler_device']}"
                if ex["straggler_device"] is not None else "unknown device"
            )
            p(f"   exchange: {ex['ops']} op(s), {ex['wait_ms']:,.1f} ms "
              f"wait; straggler {dev} (max skew {ex['max_skew']:.2f}x, "
              f"skew cost {ex['skew_ms']:,.1f} ms)")
    mesh = cp.get("mesh")
    if mesh:
        dev = (
            f"device {mesh['straggler_device']}"
            if mesh["straggler_device"] is not None else "unknown device"
        )
        share = mesh["skew_share"]
        share_s = "-" if share is None else f"{share:.0%}"
        p(f"\n== mesh: {mesh['exchange_ops']} exchange(s), "
          f"{mesh['exchange_ms']:,.1f} ms on the interconnect; straggler "
          f"{dev}; skew share of the exchange gap {share_s} "
          f"({mesh['skew_ms']:,.1f} ms a balanced partition map would "
          f"give back)")
