"""Event-log reading + aggregation: the analysis half of the obs subsystem.

Consumed by `nds_tpu/cli/profile.py` (operator breakdowns, A/B compare),
by the throughput parent (fold-in + failure classification of child-stream
event files), and by full_bench (classifying a subprocess phase failure
from the events the child wrote before dying — the parent only sees an
exit code, closing the ROADMAP gap).
"""

from __future__ import annotations

import glob
import json
import os
import re

from .. import faults
from .trace import EVENT_SCHEMA

#: injected-fault kind -> failure-taxonomy kind (faults.classify vocabulary)
_FAULT_KIND_MAP = {
    "oom": faults.DEVICE_OOM,
    "hostoom": faults.HOST_OOM,
    "io": faults.IO_TRANSIENT,
    "hang": faults.TIMEOUT,
    "crash": faults.UNKNOWN,  # simulated process death: nothing retryable
}


class MalformedEventError(ValueError):
    """An event line that is not valid JSON (other than a torn final line,
    which a crash legitimately leaves behind and readers skip)."""


#: events-<app>.jsonl (segment 0) or events-<app>.<seq>.jsonl (rotation
#: segments, Tracer._segment_path). Non-greedy app so a numeric suffix
#: parses as the seq, not the app tail.
_SEGMENT_RE = re.compile(r"^events-(?P<app>.+?)(?:\.(?P<seq>\d+))?\.jsonl$")


def segment_key(path) -> tuple:
    """(app id, rotation seq) of one event file — the chain-reassembly
    sort key. Segment 0 is the un-suffixed classic name; rotation
    segments carry a numeric seq. Unrecognized names sort by basename
    with seq 0 (never rejected: discovery must stay tolerant)."""
    base = os.path.basename(str(path))
    m = _SEGMENT_RE.match(base)
    if not m:
        return (base, 0)
    return (m.group("app"), int(m.group("seq") or 0))


def discover_event_files(trace_dir) -> list:
    """All event logs under a trace dir, ordered by (app id, rotation
    seq) so each app's segment chain reads back in emission order (plain
    name sort would put `events-a.0001.jsonl` BEFORE `events-a.jsonl`)."""
    if not trace_dir:
        return []
    return sorted(
        glob.glob(os.path.join(str(trace_dir), "events-*.jsonl")),
        key=segment_key,
    )


def iter_events(path, strict: bool = True):
    """Yield events from one JSONL file.

    A torn FINAL line (no trailing newline — the single-write+flush
    contract means only a crash mid-write can produce one) is skipped in
    both modes; with rotation this classification is deliberately
    PER-SEGMENT, so a crash that tore the final line of what later became
    a non-final segment of its chain still reads as crash evidence, not
    corruption. Any other malformed line raises MalformedEventError when
    `strict`, else is skipped."""
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    lines = raw.split("\n")
    tail = None
    if not raw.endswith("\n") and lines:
        tail = lines.pop()  # candidate torn final line
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            yield json.loads(line)
        except json.JSONDecodeError:
            if strict:
                raise MalformedEventError(
                    f"{path}:{i + 1}: malformed event line: {line[:120]!r}"
                )
    if tail:
        try:
            yield json.loads(tail)
        except json.JSONDecodeError:
            pass  # torn final line: tolerated evidence of a crash


def trace_meta_of(path):
    """The first `trace_meta` event of one event file (tolerant: None on
    an unreadable/torn/foreign file). The fold-in attribution key: every
    segment opens with its producing process's meta line carrying pid,
    emission epoch (`ts`) and — since the trace-context work — the
    `trace_id` the launcher minted for that process."""
    try:
        for ev in iter_events(path, strict=False):
            if ev.get("kind") == "trace_meta":
                return ev
            return None  # contract: meta is the FIRST line
    except OSError:
        return None
    return None


#: slack (ms) for launch-time matching: the child stamps its meta after
#: interpreter start, but clocks may disagree slightly across a remote fs
LAUNCH_TS_SLACK_MS = 5000


def meta_matches_launch(meta, pid=None, launch_ts_ms=None,
                        trace_id=None) -> bool:
    """Does one event file's trace_meta belong to the child a launcher
    recorded? The minted trace_id is authoritative when both sides carry
    one (immune to pid recycling); otherwise fall back to pid PLUS an
    emission-time check against the launch record — a recycled pid's
    leftover file from a long-dead child predates this launch and is
    rejected instead of mis-blamed."""
    if meta is None:
        return False
    if trace_id is not None and meta.get("trace_id") is not None:
        return meta["trace_id"] == trace_id
    if pid is not None and meta.get("pid") != pid:
        return False
    if launch_ts_ms is not None:
        ts = meta.get("ts")
        if ts is None or int(ts) < int(launch_ts_ms) - LAUNCH_TS_SLACK_MS:
            return False
    return pid is not None or launch_ts_ms is not None


def read_events(paths, strict: bool = True) -> list:
    """Events from one path or a list of paths (files or trace dirs),
    concatenated in file order."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    files = []
    for p in paths:
        p = str(p)
        if os.path.isdir(p):
            files.extend(discover_event_files(p))
        else:
            files.append(p)
    out = []
    for f in files:
        out.extend(iter_events(f, strict=strict))
    return out


def validate_events(events) -> list:
    """Schema problems as strings (empty == clean): unknown kinds and
    missing per-kind required fields (EVENT_SCHEMA is the contract)."""
    problems = []
    for i, ev in enumerate(events):
        kind = ev.get("kind")
        if kind is None or "ts" not in ev or "app" not in ev:
            problems.append(f"event {i}: missing ts/kind/app: {ev}")
            continue
        req = EVENT_SCHEMA.get(kind)
        if req is None:
            problems.append(f"event {i}: unknown kind {kind!r}")
            continue
        missing = [f for f in req if f not in ev]
        if missing:
            problems.append(f"event {i} ({kind}): missing fields {missing}")
    return problems


# ---------------------------------------------------------------------------
# stream summaries + failure classification (fold-in consumers)
# ---------------------------------------------------------------------------


def summarize_stream(events) -> dict:
    """Roll one (child) stream's events up for the parent's fold-in event:
    query statuses, failure kinds, and tallies the profiler also reports."""
    queries = {}
    for ev in events:
        if ev.get("kind") == "query_span":
            queries[ev.get("query")] = {
                "status": ev.get("status"),
                "failure_kind": ev.get("failure_kind"),
            }
    failed = {
        q: (v["failure_kind"] or faults.UNKNOWN)
        for q, v in queries.items()
        if v["status"] == "Failed"
    }
    return {
        "queries": len(queries),
        "completed": sum(
            1 for v in queries.values() if v["status"] != "Failed"
        ),
        "failed": failed,
        "failure_kinds": sorted(set(failed.values())),
    }


def failure_kind_from_events(events):
    """Best-effort failure classification from a stream's event log, for a
    parent that only saw a nonzero exit code: the last Failed query_span's
    kind wins (a recorded failure is the strongest evidence); only when NO
    query failed does the last injected fault's mapped kind stand in (e.g.
    a crash rule that killed the process before any span was written)."""
    failed_kind = None
    fault_kind = None
    for ev in events:
        k = ev.get("kind")
        if k == "query_span" and ev.get("status") == "Failed":
            failed_kind = ev.get("failure_kind") or faults.UNKNOWN
        elif k == "fault_injected":
            fault_kind = _FAULT_KIND_MAP.get(
                ev.get("fault_kind"), faults.UNKNOWN
            )
    return failed_kind or fault_kind


def failure_kind_from_files(paths):
    try:
        return failure_kind_from_events(read_events(paths, strict=False))
    except OSError:
        return None


# ---------------------------------------------------------------------------
# operator-level aggregation (the profiler's core)
# ---------------------------------------------------------------------------


def op_spans_with_exclusive(events) -> list:
    """op_span events with an `excl_ms` field added.

    Spans are emitted in completion (post-) order with `depth` and a
    per-executor `seq`; within one (app, query, exec_id) group a child
    completes before its parent, so exclusive time falls out of one pass:
    excl(parent at depth d) = incl - sum(incl of direct children at d+1)."""
    groups = {}
    for ev in events:
        if ev.get("kind") != "op_span":
            continue
        key = (ev.get("app"), ev.get("query"), ev.get("exec_id"))
        groups.setdefault(key, []).append(ev)
    out = []
    for spans in groups.values():
        spans.sort(key=lambda e: e.get("seq", 0))
        acc = {}  # depth -> accumulated child inclusive ms awaiting a parent
        for ev in spans:
            d = ev.get("depth", 0)
            incl = float(ev.get("dur_ms") or 0.0)
            excl = max(incl - acc.pop(d + 1, 0.0), 0.0)
            acc[d] = acc.get(d, 0.0) + incl
            ev = dict(ev)
            ev["excl_ms"] = excl
            out.append(ev)
    return out


_EMPTY_QUERY = {
    "wall_ms": None, "status": None, "runs": 0, "ops": {},
    "root_incl_ms": 0.0,
}


def profile_events(events) -> dict:
    """The aggregate the profiler renders: per-query wall/status/memory and
    per-operator breakdowns, run-wide operator totals, and tallies.

    Multi-stream semantics: profiling several streams' files together (a
    throughput run's trace dir) keys by query NAME and SUMS across streams
    — wall_ms is the total across the query's `runs` query_spans, operator
    times sum the same way (so plan time stays bounded by wall time), any
    Failed run marks the query Failed, and memory high-water is the max."""
    spans = op_spans_with_exclusive(events)
    queries = {}
    op_totals = {}
    # per-kernel dispatch totals (kernel_span events, kernel tracing mode):
    # the "which KERNEL under the hot operator" answer op_spans cannot give
    kernel_totals = {}
    for ev in spans:
        q = ev.get("query") or "<unscoped>"
        node = ev.get("node", "?")
        qrec = queries.setdefault(q, dict(_EMPTY_QUERY, ops={}))
        op = qrec["ops"].setdefault(
            node, {"count": 0, "incl_ms": 0.0, "excl_ms": 0.0, "rows": 0}
        )
        op["count"] += 1
        op["incl_ms"] += float(ev.get("dur_ms") or 0.0)
        op["excl_ms"] += ev["excl_ms"]
        if ev.get("rows") is not None:
            op["rows"] += int(ev["rows"])
        if ev.get("depth", 0) == 0:
            qrec["root_incl_ms"] += float(ev.get("dur_ms") or 0.0)
        tot = op_totals.setdefault(
            node, {"count": 0, "incl_ms": 0.0, "excl_ms": 0.0, "rows": 0}
        )
        tot["count"] += 1
        tot["incl_ms"] += float(ev.get("dur_ms") or 0.0)
        tot["excl_ms"] += ev["excl_ms"]
        if ev.get("rows") is not None:
            tot["rows"] += int(ev["rows"])
    tallies = {
        "plan_cache_hits": 0,
        "plan_cache_misses": 0,
        "catalog_loads": 0,
        "catalog_cache_hits": 0,
        "io_retries": 0,
        "ladder_rungs": 0,
        "watchdog_fires": 0,
        "faults_injected": 0,
        "blocked_union_windows": 0,
        "spill_ops": 0,
        "spill_bytes_in": 0,
        "spill_bytes_out": 0,
        "spill_evictions": 0,
        "exchange_ops": 0,
        "exchange_bytes": 0,
        "exchange_retries": 0,
        "exchange_max_skew": 0.0,
        "mesh_fallbacks": 0,
        "lake_commits": 0,
        "lake_commit_rebases": 0,
        "lake_commit_conflicts": 0,
        "lake_vacuums": 0,
        "lake_vacuum_files": 0,
        "exec_cache_hits": 0,
        "exec_cache_misses": 0,
        "aot_disk_hits": 0,
        "aot_misses": 0,
        "aot_stores": 0,
        "aot_quarantined": 0,
        "aot_evictions": 0,
        "pipelines_fused": 0,
        "pipelines_eager": 0,
        "mem_watermarks": 0,
    }
    budget = {
        "verdicts": {},  # verdict -> statement count
        "max_peak_bytes": 0,
        "max_budget_bytes": 0,
    }
    feedback = {
        "lookups": 0,       # store probes at budget time (mode=on)
        "hits": 0,          # probes that found a recorded actual
        "overrides": 0,     # per-node estimates actually replaced
        "records": 0,       # actuals recorded at execution time
        "err_n": 0,         # records that carried an |log(est/actual)|
        "err_sum": 0.0,
        "err_max": 0.0,
        # node class -> {n, err_sum, err_max}: the mergeable summary
        # behind `profile --accuracy` (full distributions come from the
        # raw op_spans, which compaction folds away)
        "by_node": {},
    }
    for ev in events:
        k = ev.get("kind")
        if k == "query_span":
            q = queries.setdefault(
                ev.get("query") or "<unscoped>", dict(_EMPTY_QUERY, ops={})
            )
            q["wall_ms"] = (q["wall_ms"] or 0.0) + float(ev.get("dur_ms") or 0.0)
            q["runs"] += 1
            if q["status"] != "Failed":  # any failed run surfaces
                q["status"] = ev.get("status")
            if ev.get("failure_kind"):
                q["failure_kind"] = ev["failure_kind"]
            if ev.get("mem_hw_bytes") is not None:
                # mem_source describes the run that HOLDS the high-water
                # (merge_profiles mirrors this, so compacted and raw
                # profiles of the same events agree on it)
                v = int(ev["mem_hw_bytes"])
                if "mem_hw_bytes" not in q or v > q["mem_hw_bytes"]:
                    q["mem_hw_bytes"] = v
                    q["mem_source"] = ev.get("mem_source")
        elif k == "plan_cache":
            tallies["plan_cache_hits" if ev.get("hit") else "plan_cache_misses"] += 1
        elif k == "catalog_load":
            tallies["catalog_loads"] += 1
            if ev.get("cache") == "hit":
                tallies["catalog_cache_hits"] += 1
        elif k == "io_retry":
            tallies["io_retries"] += 1
        elif k == "ladder_rung":
            tallies["ladder_rungs"] += 1
        elif k == "watchdog_fire":
            tallies["watchdog_fires"] += 1
        elif k == "fault_injected":
            tallies["faults_injected"] += 1
        elif k == "blocked_union":
            tallies["blocked_union_windows"] += int(ev.get("windows") or 0)
        elif k == "exchange":
            tallies["exchange_ops"] += 1
            tallies["exchange_bytes"] += int(ev.get("bytes_moved") or 0)
            tallies["exchange_retries"] += int(ev.get("retries") or 0)
            try:
                skew = float(ev.get("skew") or 0.0)
            except (TypeError, ValueError):
                skew = 0.0
            if skew > tallies["exchange_max_skew"]:
                tallies["exchange_max_skew"] = skew
        elif k == "mesh_fallback":
            tallies["mesh_fallbacks"] += 1
        elif k == "spill":
            tallies["spill_ops"] += 1
            tallies["spill_bytes_in"] += int(ev.get("bytes_in") or 0)
            tallies["spill_bytes_out"] += int(ev.get("bytes_out") or 0)
            tallies["spill_evictions"] += int(ev.get("evictions") or 0)
        elif k == "lake_commit":
            if ev.get("conflict"):
                tallies["lake_commit_conflicts"] += 1
            else:
                tallies["lake_commits"] += 1
                if ev.get("rebased"):
                    tallies["lake_commit_rebases"] += 1
        elif k == "lake_vacuum":
            tallies["lake_vacuums"] += 1
            tallies["lake_vacuum_files"] += int(ev.get("files_removed") or 0)
        elif k == "exec_cache":
            tallies[
                "exec_cache_hits" if ev.get("hit") else "exec_cache_misses"
            ] += 1
        elif k == "aot_cache":
            op, result = ev.get("op"), ev.get("result")
            if op == "load":
                if result == "hit":
                    tallies["aot_disk_hits"] += 1
                elif result == "quarantined":
                    tallies["aot_quarantined"] += 1
                else:
                    tallies["aot_misses"] += 1
            elif op == "store" and result == "stored":
                tallies["aot_stores"] += 1
            elif op == "evict":
                tallies["aot_evictions"] += int(ev.get("entries") or 0)
        elif k == "pipeline_span":
            tallies[
                "pipelines_fused" if ev.get("fused") else "pipelines_eager"
            ] += 1
        elif k == "kernel_span":
            kt = kernel_totals.setdefault(
                ev.get("kernel") or "<unknown>",
                {"count": 0, "dur_ms": 0.0, "n_rows": 0},
            )
            kt["count"] += 1
            kt["dur_ms"] += float(ev.get("dur_ms") or 0.0)
            kt["n_rows"] += int(ev.get("n") or 0)
        elif k == "plan_budget":
            v = ev.get("verdict") or "<unknown>"
            budget["verdicts"][v] = budget["verdicts"].get(v, 0) + 1
            budget["max_peak_bytes"] = max(
                budget["max_peak_bytes"], int(ev.get("peak_bytes") or 0)
            )
            budget["max_budget_bytes"] = max(
                budget["max_budget_bytes"], int(ev.get("budget_bytes") or 0)
            )
        elif k == "plan_feedback":
            op = ev.get("op")
            if op in ("consume", "annotate"):
                feedback["lookups"] += int(ev.get("lookups") or 0)
                feedback["hits"] += int(ev.get("hits") or 0)
                feedback["overrides"] += int(ev.get("overrides") or 0)
            elif op == "record":
                feedback["records"] += 1
                err = ev.get("abs_log_err")
                if err is not None:
                    e = float(err)
                    feedback["err_n"] += 1
                    feedback["err_sum"] += e
                    if e > feedback["err_max"]:
                        feedback["err_max"] = e
                    node = ev.get("node") or "<unknown>"
                    rec = feedback["by_node"].setdefault(
                        node, {"n": 0, "err_sum": 0.0, "err_max": 0.0}
                    )
                    rec["n"] += 1
                    rec["err_sum"] += e
                    if e > rec["err_max"]:
                        rec["err_max"] = e
        elif k == "mem_watermark":
            tallies["mem_watermarks"] += 1
    return {
        "queries": queries,
        "op_totals": op_totals,
        "kernel_totals": kernel_totals,
        "tallies": tallies,
        "plan_budget": budget,
        "feedback": feedback,
    }


def exec_cache_hit_rate(prof: dict):
    """Executable-cache hit rate of a profiled run, or None when the run
    recorded no exec_cache probes (fusion off / untraced). The CI
    microbench guard (`profile --min_exec_cache_hit_rate`) reads this."""
    t = prof["tallies"]
    probes = t["exec_cache_hits"] + t["exec_cache_misses"]
    if probes == 0:
        return None
    return t["exec_cache_hits"] / probes


def feedback_hit_rate(prof: dict):
    """Feedback-store hit rate of a profiled run (budget-time lookups
    that found a recorded actual), or None when the run did no lookups
    (plan_feedback off/record — record mode never probes). The bench OUT
    line and `profile --bench` headline read this."""
    fb = prof.get("feedback") or {}
    lookups = fb.get("lookups") or 0
    if not lookups:
        return None
    return (fb.get("hits") or 0) / lookups


def feedback_err_mean(prof: dict):
    """Mean |log(est/actual)| over the run's recorded feedback samples,
    or None when nothing carried an error (no estimates annotated)."""
    fb = prof.get("feedback") or {}
    n = fb.get("err_n") or 0
    if not n:
        return None
    return float(fb.get("err_sum") or 0.0) / n


def aot_disk_hit_rate(prof: dict):
    """Persistent-executable-cache disk hit rate of a profiled run, or
    None when no aot_cache load probes were recorded (cache disabled /
    untraced). The two-process microbench gate in tools/fuse_microbench.py
    reads this from the FRESH process's trace: a warmed fleet's cold
    dispatches must resolve from disk, not recompile."""
    t = prof["tallies"]
    probes = t.get("aot_disk_hits", 0) + t.get("aot_misses", 0)
    if probes == 0:
        return None
    return t.get("aot_disk_hits", 0) / probes


# ---------------------------------------------------------------------------
# trace-dir compaction: fold closed rotation segments into summary artifacts
# ---------------------------------------------------------------------------

#: compaction summary artifact (one per app chain) — the pre-aggregated
#: profile of the folded segments plus provenance
COMPACT_PREFIX = "compact-"


def discover_compact_files(trace_dir) -> list:
    if not trace_dir:
        return []
    return sorted(
        glob.glob(os.path.join(str(trace_dir), f"{COMPACT_PREFIX}*.json"))
    )


def read_compact(path) -> dict:
    """One compaction artifact ({"compact": 1, "app", "segments",
    "events", "profile"}); raises ValueError on a non-artifact OR an
    artifact whose profile is structurally unusable (e.g. a torn/edited
    file with "profile": null) — merge_profiles must never see it, so
    every consumer fails through its ValueError path instead of an
    AttributeError deep inside the merge."""
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    prof = raw.get("profile") if isinstance(raw, dict) else None
    if not isinstance(raw, dict) or raw.get("compact") != 1 or not isinstance(
        prof, dict
    ):
        raise ValueError(f"{path}: not a profile-compaction artifact")
    for key in ("queries", "op_totals", "kernel_totals", "tallies",
                "plan_budget", "feedback"):
        v = prof.get(key)
        if v is None:
            continue
        bad = not isinstance(v, dict)
        if not bad and key in ("queries", "op_totals", "kernel_totals"):
            bad = any(not isinstance(x, dict) for x in v.values())
        if not bad and key == "tallies":
            bad = any(not isinstance(x, (int, float)) for x in v.values())
        if bad:
            raise ValueError(
                f"{path}: compaction artifact with malformed "
                f"profile[{key!r}]"
            )
    return raw


def _merge_op(dst: dict, src: dict):
    dst["count"] = dst.get("count", 0) + int(src.get("count") or 0)
    dst["incl_ms"] = dst.get("incl_ms", 0.0) + float(src.get("incl_ms") or 0.0)
    dst["excl_ms"] = dst.get("excl_ms", 0.0) + float(src.get("excl_ms") or 0.0)
    dst["rows"] = dst.get("rows", 0) + int(src.get("rows") or 0)


def merge_profiles(base: dict, extra: dict) -> dict:
    """Merge two `profile_events` aggregates with the SAME multi-stream
    semantics profiling the raw files together would give: per-query
    wall/runs/operator times SUM, any Failed run surfaces, memory
    high-water is the max, tallies/verdict counts add. This is what makes
    `profile` over a compacted trace dir equal the uncompacted profile
    for the summary fields. Returns `base`, mutated."""
    for q, src in (extra.get("queries") or {}).items():
        dst = base.setdefault("queries", {}).setdefault(
            q, dict(_EMPTY_QUERY, ops={})
        )
        if src.get("wall_ms") is not None:
            dst["wall_ms"] = (dst.get("wall_ms") or 0.0) + float(src["wall_ms"])
        dst["runs"] = dst.get("runs", 0) + int(src.get("runs") or 0)
        dst["root_incl_ms"] = (
            dst.get("root_incl_ms", 0.0) + float(src.get("root_incl_ms") or 0.0)
        )
        if dst.get("status") != "Failed":  # any failed run surfaces
            dst["status"] = src.get("status") or dst.get("status")
        if src.get("failure_kind"):
            dst["failure_kind"] = src["failure_kind"]
        if src.get("mem_hw_bytes") is not None and (
            "mem_hw_bytes" not in dst
            or int(src["mem_hw_bytes"]) > int(dst.get("mem_hw_bytes") or 0)
        ):
            dst["mem_hw_bytes"] = int(src["mem_hw_bytes"])
            dst["mem_source"] = src.get("mem_source")
        for node, op in (src.get("ops") or {}).items():
            _merge_op(
                dst["ops"].setdefault(
                    node,
                    {"count": 0, "incl_ms": 0.0, "excl_ms": 0.0, "rows": 0},
                ),
                op,
            )
    for name, src in (extra.get("op_totals") or {}).items():
        _merge_op(base.setdefault("op_totals", {}).setdefault(name, {}), src)
    for name, src in (extra.get("kernel_totals") or {}).items():
        dst = base.setdefault("kernel_totals", {}).setdefault(name, {})
        dst["count"] = dst.get("count", 0) + int(src.get("count") or 0)
        dst["dur_ms"] = dst.get("dur_ms", 0.0) + float(src.get("dur_ms") or 0.0)
        dst["n_rows"] = dst.get("n_rows", 0) + int(src.get("n_rows") or 0)
    for name, v in (extra.get("tallies") or {}).items():
        base.setdefault("tallies", {})
        if name == "exchange_max_skew":
            # a ratio, not a count: the merged profile reports the worst
            # imbalance any stream saw, exactly as one raw pass would
            base["tallies"][name] = max(base["tallies"].get(name, 0.0), v)
        else:
            base["tallies"][name] = base["tallies"].get(name, 0) + v
    pb_src = extra.get("plan_budget") or {}
    pb_dst = base.setdefault(
        "plan_budget",
        {"verdicts": {}, "max_peak_bytes": 0, "max_budget_bytes": 0},
    )
    for v, n in (pb_src.get("verdicts") or {}).items():
        pb_dst["verdicts"][v] = pb_dst["verdicts"].get(v, 0) + n
    for key in ("max_peak_bytes", "max_budget_bytes"):
        pb_dst[key] = max(pb_dst.get(key, 0), int(pb_src.get(key) or 0))
    fb_src = extra.get("feedback") or {}
    fb_dst = base.setdefault("feedback", {
        "lookups": 0, "hits": 0, "overrides": 0, "records": 0,
        "err_n": 0, "err_sum": 0.0, "err_max": 0.0, "by_node": {},
    })
    for key in ("lookups", "hits", "overrides", "records", "err_n"):
        fb_dst[key] = fb_dst.get(key, 0) + int(fb_src.get(key) or 0)
    fb_dst["err_sum"] = (
        fb_dst.get("err_sum", 0.0) + float(fb_src.get("err_sum") or 0.0)
    )
    fb_dst["err_max"] = max(
        fb_dst.get("err_max", 0.0), float(fb_src.get("err_max") or 0.0)
    )
    for node, src in (fb_src.get("by_node") or {}).items():
        dst = fb_dst.setdefault("by_node", {}).setdefault(
            node, {"n": 0, "err_sum": 0.0, "err_max": 0.0}
        )
        dst["n"] = dst.get("n", 0) + int(src.get("n") or 0)
        dst["err_sum"] = (
            dst.get("err_sum", 0.0) + float(src.get("err_sum") or 0.0)
        )
        dst["err_max"] = max(
            dst.get("err_max", 0.0), float(src.get("err_max") or 0.0)
        )
    return base


def load_profile(paths, strict: bool = True, events_hook=None) -> dict:
    """The profile aggregate of raw event files AND compaction artifacts
    under `paths` — `profile_events` over the events, then every
    artifact's saved profile merged in. THE one implementation of
    "profile a (partially) compacted dir": the profiler CLI routes here
    too, passing `events_hook(events)` to schema-validate the raw half
    before aggregation (artifacts were validated when their segments
    folded — compact_trace_dir refuses schema-dirty segments).

    Safe against a CONCURRENT `profile compact` of the same dir (the
    documented fleet mode): dir-discovered segments are read first and
    individually tolerate vanishing mid-read (the compactor deleted them
    — their events are in the artifact, whose atomic commit strictly
    precedes the delete), artifacts are discovered AFTER the reads, and
    any raw segment that both got read AND appears in an artifact's
    `segments` provenance is dropped from the raw half before profiling
    (same dedup that makes a crashed compactor's half-state count once).
    Explicitly named files keep strict semantics — a missing path the
    caller asked for is still an error."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    events, compacts = [], []
    per_seg = []  # (basename, events) of dir-discovered segments
    for p in paths:
        p = str(p)
        if os.path.isdir(p):
            for f in discover_event_files(p):
                try:
                    per_seg.append(
                        (os.path.basename(f), list(iter_events(f, strict=strict)))
                    )
                except FileNotFoundError:
                    pass  # raced a concurrent compact: folded into an artifact
            compacts.extend(discover_compact_files(p))
        elif os.path.basename(p).startswith(COMPACT_PREFIX) and p.endswith(
            ".json"
        ):
            compacts.append(p)
        else:
            events.extend(iter_events(p, strict=strict))
    artifacts = [read_compact(c) for c in compacts]
    folded = set()
    for a in artifacts:
        folded.update(a.get("segments") or [])
    for base, evs in per_seg:
        if base not in folded:  # read raw AND folded would count twice
            events.extend(evs)
    if events_hook is not None:
        events_hook(events)
    prof = profile_events(events)
    for a in artifacts:
        merge_profiles(prof, a["profile"])
    return prof


def compact_trace_dir(trace_dir, fold_open: bool = False,
                      dry_run: bool = False):
    """Fold rotation segments into per-app `compact-<app>.json` summary
    artifacts and DELETE the folded raw files, bounding a long-running
    fleet's event-log disk at ~one open segment per live app.

    By default only CLOSED segments fold (everything but each chain's
    highest-seq segment, which a live tracer may still be appending to);
    `fold_open=True` folds whole chains (post-run compaction). Re-running
    merges new closed segments into the existing artifact. A segment with
    mid-file corruption is left in place for forensics and reported in
    `skipped` — compaction never destroys evidence it could not read.

    Crash safety: the artifact commits atomically BEFORE the raw deletes,
    and its `segments` provenance list is consulted on the next run — a
    segment whose basename is already recorded was folded by a run that
    died mid-delete, so it is removed without re-merging (no double
    count, ever).

    `dry_run` runs the exact same selection + readability classification
    but writes and deletes nothing (the `profile compact --dry_run`
    preview shares this one implementation so it cannot drift).

    Returns (folded, skipped): folded = [(app, [paths])...],
    skipped = [(path, reason)...]."""
    from ..io.fs import fs_open_atomic

    chains = {}
    for f in discover_event_files(trace_dir):
        app, seq = segment_key(f)
        chains.setdefault(app, []).append((seq, f))
    folded, skipped = [], []
    for app, segs in sorted(chains.items()):
        segs.sort()
        victims = [f for _, f in (segs if fold_open else segs[:-1])]
        if not victims:
            continue
        artifact = os.path.join(str(trace_dir), f"{COMPACT_PREFIX}{app}.json")
        try:
            prior = read_compact(artifact) if os.path.exists(artifact) else None
        except (OSError, ValueError) as exc:
            # an unreadable/foreign prior artifact: folding into it would
            # overwrite whatever it held — skip this chain, keep going on
            # the others (a fleet's disk must not hinge on one bad file)
            skipped.append((artifact, str(exc)))
            continue
        already = set((prior or {}).get("segments") or [])
        stale = [f for f in victims if os.path.basename(f) in already]
        victims = [f for f in victims if os.path.basename(f) not in already]
        if not dry_run:
            for f in stale:
                os.remove(f)  # folded by a crashed run: finish its delete
        events, ok_files = [], []
        for f in victims:
            try:
                evs = list(iter_events(f, strict=True))
            except MalformedEventError as exc:
                skipped.append((f, str(exc)))
                continue
            # schema-validate BEFORE folding: an artifact only ever holds
            # schema-clean events, so `profile --check` keeps its teeth
            # over compacted dirs (the raw spans it would have flagged are
            # left in place and reported instead of silently absorbed)
            problems = validate_events(evs)
            if problems:
                skipped.append((f, f"schema: {problems[0]}"))
                continue
            events.extend(evs)
            ok_files.append(f)
        if not ok_files:
            if stale:
                folded.append((app, stale))
            continue
        if dry_run:
            folded.append((app, stale + ok_files))
            continue
        prof = profile_events(events)
        if prior is not None:
            # merge INTO the prior profile so repeated compaction rounds
            # accumulate exactly like one bigger round would have
            prof = merge_profiles(prior["profile"], prof)
        payload = {
            "compact": 1,
            "app": app,
            "segments": sorted(already)
            + [os.path.basename(f) for f in ok_files],
            "events": int((prior or {}).get("events") or 0) + len(events),
            "profile": prof,
        }
        with fs_open_atomic(artifact, "w") as fh:
            json.dump(payload, fh)
        for f in ok_files:
            os.remove(f)
        folded.append((app, stale + ok_files))
    return folded, skipped


def compare_profiles(old: dict, new: dict, ratio: float = 1.25,
                     min_ms: float = 50.0) -> list:
    """Per-query wall-time and per-(query, operator) exclusive-time
    regressions between two profiles. A regression flags when new >= old *
    `ratio` AND the absolute delta >= `min_ms` (tiny operators jitter).
    Returns records sorted worst-first; disappearing/appearing queries are
    reported as `status_change` records."""
    out = []
    oq, nq = old["queries"], new["queries"]
    for q in sorted(set(oq) | set(nq)):
        o, n = oq.get(q), nq.get(q)
        if o is None or n is None:
            out.append({
                "level": "query", "query": q, "change": "status_change",
                "detail": "only in new run" if o is None else "only in old run",
            })
            continue
        if (o.get("status") != "Failed") and n.get("status") == "Failed":
            out.append({
                "level": "query", "query": q, "change": "status_change",
                "detail": f"now Failed ({n.get('failure_kind', 'unknown')})",
            })
            continue
        ow, nw = o.get("wall_ms"), n.get("wall_ms")
        if ow and nw and nw >= ow * ratio and nw - ow >= min_ms:
            out.append({
                "level": "query", "query": q, "change": "regression",
                "old_ms": ow, "new_ms": nw, "ratio": nw / ow,
            })
        for node in sorted(set(o["ops"]) | set(n["ops"])):
            oe = o["ops"].get(node, {}).get("excl_ms", 0.0)
            ne = n["ops"].get(node, {}).get("excl_ms", 0.0)
            if oe and ne >= oe * ratio and ne - oe >= min_ms:
                out.append({
                    "level": "operator", "query": q, "node": node,
                    "change": "regression",
                    "old_ms": oe, "new_ms": ne, "ratio": ne / oe,
                })
    out.sort(key=lambda r: -r.get("ratio", float("inf")))
    return out
