"""Structured run-wide event tracing: the engine's own observability stream.

The reference harness is measured entirely through Spark's instrumentation —
event logs, per-task metrics, and the RAPIDS profiling/qualification tools
that post-process them. This engine has no Spark underneath, so the
equivalent seam lives here: a `Tracer` appends JSON-lines events to
`events-<appid>.jsonl` under a trace directory (`NDS_TRACE_DIR` env / conf
`engine.trace_dir`), one self-contained JSON object per line, and
`nds_tpu/cli/profile.py` is the post-processor (the local analogue of the
reference's profiling tool over Spark event logs).

Zero-cost contract: with no trace dir configured, `tracer_from_conf` returns
None, `Session.tracer` is None, and every instrumentation point in the hot
path is a single attribute-load + `is None` check.

Crash-safety contract: each event is written with ONE `write()` call of a
complete line and flushed, so a reader never sees an interleaved line from
two threads and a crashed process leaves at most one torn FINAL line (which
readers tolerate; any earlier malformed line is a hard error —
`obs.reader.iter_events`).

Event taxonomy (golden schema — tests/test_obs.py asserts it):
every event carries `ts` (epoch ms), `kind`, `app`, and (when a query scope
is active, `faults.scope`) `query`; per-kind required fields are listed in
EVENT_SCHEMA below.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from .. import faults
from .. import __version__

#: kind -> tuple of required per-kind fields (beyond ts/kind/app).
#: Optional fields events may also carry are documented in README
#: "Observability". This mapping is the schema contract the golden test and
#: `profile --check`/`obs.reader.validate_events` enforce.
EVENT_SCHEMA = {
    # first line of every file: identifies the producing process
    "trace_meta": ("pid", "version"),
    # one per executed plan node (inclusive wall time; children nest inside)
    "op_span": ("exec_id", "seq", "depth", "node", "explain", "dur_ms",
                "rows", "est_bytes"),
    # one per benchmarked query/function (BenchReport.report_on)
    "query_span": ("query", "dur_ms", "status", "retries"),
    # catalog table load (cache: "hit" | "partial" | "miss")
    "catalog_load": ("table", "columns", "loaded", "rows", "dur_ms", "cache"),
    # session plan-result cache probe on a cacheable plan node
    "plan_cache": ("node", "hit"),
    # blocked union-aggregation completed (PR 1 window stats)
    "blocked_union": ("windows", "window_rows", "total_rows"),
    # one fused-pipeline execution (fused=False: eager per-stage fallback;
    # also carries `agg` when the pipeline has a fused aggregate tail)
    "pipeline_span": ("stages", "fused", "dur_ms"),
    # one synchronized device-kernel dispatch (ops/kernels.py hot kernels;
    # only with kernel tracing on — engine.trace_kernels/NDS_TRACE_KERNELS —
    # because the measurement blocks on the result, trading pipelining for
    # per-kernel attribution below plan-node op_spans). `n` is the leading
    # input length. Also records the Pallas-vs-jnp promotion measurements
    # (kernel "segment_<fn>:jnp" / ":pallas", exec._pallas_promoted).
    "kernel_span": ("kernel", "dur_ms", "n"),
    # executable-cache probe for a pipeline (hit=True: an executable for
    # this (structure, dtypes, bucket) already existed this session)
    "exec_cache": ("pipeline", "bucket", "hit"),
    # a fault-injection rule fired (faults.FaultRegistry)
    "fault_injected": ("site", "fault_kind"),
    # one degradation-ladder rung taken (BenchReport)
    "ladder_rung": ("query", "rung", "failure_kind"),
    # the per-query watchdog abandoned a hung attempt
    "watchdog_fire": ("query", "budget_s"),
    # a transient remote-IO failure was retried (io/fs.py)
    "io_retry": ("path", "error", "delay_s"),
    # full_bench orchestrator phase boundary (event: "begin" | "end")
    "phase": ("phase", "event"),
    # parent fold-in of one throughput child stream's event file(s)
    "child_stream": ("stream", "files", "queries", "completed", "failed"),
    # the plan verifier checked a statement's plan at one rewrite stage
    # (engine.verify_plans; ok=False events also carry violations/first)
    "plan_verify": ("stage", "ok"),
    # the static plan budgeter's per-statement verdict (engine.plan_budget;
    # analysis/budget.py): modeled peak vs the working-set budget, plus
    # peak_blocked_bytes/window_rows/nodes detail
    "plan_budget": ("verdict", "peak_bytes", "budget_bytes"),
    # the host-RSS watermark sampler pre-empted memory pressure mid-query
    # (report.py; shrinks the blocked-union window before the allocator
    # fails)
    "mem_watermark": ("rss_bytes", "watermark_bytes"),
}

#: kinds kept in EVENT_SCHEMA for old-log readers but no longer emitted by
#: the current tree; the golden-sync test (tests/test_analysis.py) requires
#: every NON-deprecated kind to have a live emission site, and every
#: emitted kind to be in EVENT_SCHEMA
DEPRECATED_EVENT_KINDS = frozenset()


def resolve_trace_dir(conf: dict | None = None) -> str | None:
    """Trace directory from conf `engine.trace_dir`, else NDS_TRACE_DIR;
    None (tracing disabled) when neither is set."""
    v = None
    if conf:
        v = conf.get("engine.trace_dir")
    v = v or os.environ.get("NDS_TRACE_DIR")
    return str(v) if v else None


def resolve_kernel_trace(conf: dict | None = None) -> bool:
    """Per-kernel dispatch timing (conf `engine.trace_kernels`, env
    NDS_TRACE_KERNELS). Off by default: each traced kernel call blocks on
    its result, so this is a profiling mode, not a steady-state default."""
    v = None
    if conf:
        v = conf.get("engine.trace_kernels")
    if v is None:
        v = os.environ.get("NDS_TRACE_KERNELS")
    return str(v).lower() in ("1", "on", "true") if v is not None else False


def default_app_id() -> str:
    """Unique per-tracer app id: pid + epoch second + random suffix (two
    thread-mode throughput streams in one process must not collide)."""
    return f"nds-tpu-{os.getpid()}-{int(time.time())}-{uuid.uuid4().hex[:6]}"


class Tracer:
    """Append-only JSON-lines event writer (or an in-memory collector when
    `trace_dir` is None — the dev-tool mode tools/trace_query.py uses).

    Thread-safe: a lock serializes writes, and each event line is emitted
    with a single write() + flush so concurrent streams/threads sharing a
    tracer never interleave mid-line."""

    def __init__(self, trace_dir: str | None = None, app_id: str | None = None,
                 kernel_spans: bool = False):
        self.app_id = app_id or default_app_id()
        self.trace_dir = trace_dir
        # opt-in per-kernel dispatch timing: the ops.kernels instrumentation
        # only fires when the thread-bound tracer carries this flag
        self.kernel_spans = kernel_spans
        self.path = (
            os.path.join(trace_dir, f"events-{self.app_id}.jsonl")
            if trace_dir
            else None
        )
        self.events: list[dict] | None = None if trace_dir else []
        self._fh = None
        self._lock = threading.Lock()
        self._broken = False
        if trace_dir:
            # eager meta line: the file exists (and is discoverable by a
            # parent/orchestrator) even if the process dies before its
            # first real event
            self.emit("trace_meta", pid=os.getpid(), version=__version__)

    # ------------------------------------------------------------------
    def emit(self, kind: str, **fields):
        """Record one event. `ts`/`kind`/`app` are added here; `query` is
        added from the active faults.scope when the caller didn't pass it."""
        ev = {"ts": int(time.time() * 1000), "kind": kind, "app": self.app_id}
        if "query" not in fields:
            scope = faults.current_scope()
            if scope is not None:
                ev["query"] = scope
        ev.update(fields)
        line = json.dumps(ev, default=str)
        with self._lock:
            if self.events is not None:
                self.events.append(ev)
                return
            if self._broken:
                return
            try:
                if self._fh is None:
                    parent = os.path.dirname(self.path)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as exc:
                # observability must never take the benchmark down: an
                # unwritable trace dir disables this tracer, loudly, once
                self._broken = True
                print(f"obs: disabling tracer ({self.path}: {exc})")

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def tracer_from_conf(conf: dict | None = None, app_id: str | None = None):
    """A file-backed Tracer when a trace dir is configured, else None (the
    zero-cost disabled state every instrumentation point checks for)."""
    d = resolve_trace_dir(conf)
    if not d:
        return None
    return Tracer(d, app_id=app_id, kernel_spans=resolve_kernel_trace(conf))


# ---------------------------------------------------------------------------
# thread-local binding: layers without a Session in hand (faults, io/fs)
# reach the right stream's tracer through `current()`
# ---------------------------------------------------------------------------

_tls = threading.local()


class bind:
    """Context manager binding a tracer (or None: no-op) to this thread so
    session-less layers (fault registry, fs retries) can emit into the
    stream that is actually running. Harness loops bind their session's
    tracer around query execution; BenchReport re-binds inside its watchdog
    worker thread (thread-locals don't inherit)."""

    def __init__(self, tracer: Tracer | None):
        self.tracer = tracer

    def __enter__(self):
        self.prev = getattr(_tls, "tracer", None)
        _tls.tracer = self.tracer
        return self.tracer

    def __exit__(self, *exc):
        _tls.tracer = self.prev
        return False


def current() -> Tracer | None:
    """The tracer bound to this thread, or None (events dropped)."""
    return getattr(_tls, "tracer", None)
