"""Structured run-wide event tracing: the engine's own observability stream.

The reference harness is measured entirely through Spark's instrumentation —
event logs, per-task metrics, and the RAPIDS profiling/qualification tools
that post-process them. This engine has no Spark underneath, so the
equivalent seam lives here: a `Tracer` appends JSON-lines events to
`events-<appid>.jsonl` under a trace directory (`NDS_TRACE_DIR` env / conf
`engine.trace_dir`), one self-contained JSON object per line — rotating to
`events-<appid>.<seq>.jsonl` segments at `engine.trace_rotate_bytes` so
long-running fleets can compact closed segments (`profile compact`) — and
`nds_tpu/cli/profile.py` is the post-processor (the local analogue of the
reference's profiling tool over Spark event logs). The LIVE half is
`obs/metrics.py`: an optional MetricsSink on the same emit seam feeds the
`/metrics` + `/statusz` endpoint while the run is still going.

Near-zero-cost contract (amended by the flight recorder): with no trace
dir and no metrics port configured, `tracer_from_conf` now returns a
RING-ONLY tracer — events are built and appended to the process-wide
flight-recorder ring (obs/flight.py: one bounded deque append, no file,
no in-memory list) so a crash or hang ALWAYS leaves a failure bundle
behind, trace dir or not. Setting `engine.flight_recorder` /
NDS_FLIGHT_RECORDER to off restores the historical contract
(`tracer_from_conf` -> None, every instrumentation point one `is None`
check). The ring's per-event cost is budgeted in CI (<2% of SF0.01
stream wall — the tier1 diagnosis gate).

Trace context: every tracer carries a `TraceContext` (trace_id + parent)
and `emit` stamps `trace_id` on every event. Entry points mint one
(power/throughput/full_bench/serve request/DM function — via
`tracer_from_conf`, or explicitly); subprocess launchers export it as
NDS_TRACE_CONTEXT so a child process ADOPTS the exact context its parent
minted for it, and child event files fold by trace_id instead of the
pid-recycling-prone pid match.

Crash-safety contract: each event is written with ONE `write()` call of a
complete line and flushed, so a reader never sees an interleaved line from
two threads and a crashed process leaves at most one torn FINAL line (which
readers tolerate; any earlier malformed line is a hard error —
`obs.reader.iter_events`).

Event taxonomy (golden schema — tests/test_obs.py asserts it):
every event carries `ts` (epoch ms), `kind`, `app`, the stamped
CONTEXT_FIELDS (`trace_id`; see TraceContext), and (when a query scope
is active, `faults.scope`) `query`; per-kind required fields are listed in
EVENT_SCHEMA below. `trace_id` is stamped centrally by `Tracer.emit` —
emission sites must NOT pass it ad hoc unless the kind declares it in
EVENT_SCHEMA (the `trace-event-schema` lint rule enforces this).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from .. import faults
from .. import __version__
from ..engine.lockdebug import make_lock

#: kind -> tuple of required per-kind fields (beyond ts/kind/app).
#: Optional fields events may also carry are documented in README
#: "Observability". This mapping is the schema contract the golden test and
#: `profile --check`/`obs.reader.validate_events` enforce.
EVENT_SCHEMA = {
    # first line of every file: identifies the producing process
    "trace_meta": ("pid", "version"),
    # one per executed plan node (inclusive wall time; children nest inside)
    "op_span": ("exec_id", "seq", "depth", "node", "explain", "dur_ms",
                "rows", "est_bytes"),
    # one per benchmarked query/function (BenchReport.report_on)
    "query_span": ("query", "dur_ms", "status", "retries"),
    # catalog table load (cache: "hit" | "partial" | "miss")
    "catalog_load": ("table", "columns", "loaded", "rows", "dur_ms", "cache"),
    # session plan-result cache probe on a cacheable plan node
    "plan_cache": ("node", "hit"),
    # blocked union-aggregation completed (PR 1 window stats)
    "blocked_union": ("windows", "window_rows", "total_rows"),
    # one fused-pipeline execution (fused=False: eager per-stage fallback;
    # also carries `agg` when the pipeline has a fused aggregate tail)
    "pipeline_span": ("stages", "fused", "dur_ms"),
    # one synchronized device-kernel dispatch (ops/kernels.py hot kernels;
    # only with kernel tracing on — engine.trace_kernels/NDS_TRACE_KERNELS —
    # because the measurement blocks on the result, trading pipelining for
    # per-kernel attribution below plan-node op_spans). `n` is the leading
    # input length. Also records the Pallas-vs-jnp promotion measurements
    # (kernel "segment_<fn>:jnp" / ":pallas", exec._pallas_promoted).
    "kernel_span": ("kernel", "dur_ms", "n"),
    # executable-cache probe for a pipeline (hit=True: an executable for
    # this (structure, dtypes, bucket) already existed this session)
    "exec_cache": ("pipeline", "bucket", "hit"),
    # persistent AOT executable cache activity (engine/aotcache.py):
    # op "load" (result hit | miss | key_mismatch | quarantined), "store"
    # (stored | io_error | unserializable), "evict", "vacuum". Optional:
    # bytes, dur_ms, key, entries, removed, error. A `load`/`hit` event in
    # a fresh process is the trace-level evidence an executable came from
    # disk instead of a recompile (the two-process microbench gate reads
    # exactly this).
    "aot_cache": ("op", "result"),
    # a fault-injection rule fired (faults.FaultRegistry)
    "fault_injected": ("site", "fault_kind"),
    # one degradation-ladder rung taken (BenchReport). Optional:
    # attempt_ms (the FAILED attempt's wall this rung recovers from —
    # the critical-path ladder-retry cause), delay_s (backoff rungs)
    "ladder_rung": ("query", "rung", "failure_kind"),
    # the per-query watchdog abandoned a hung attempt
    "watchdog_fire": ("query", "budget_s"),
    # a transient remote-IO failure was retried (io/fs.py)
    "io_retry": ("path", "error", "delay_s"),
    # full_bench orchestrator phase boundary (event: "begin" | "end")
    "phase": ("phase", "event"),
    # parent fold-in of one throughput child stream's event file(s)
    "child_stream": ("stream", "files", "queries", "completed", "failed"),
    # the plan verifier checked a statement's plan at one rewrite stage
    # (engine.verify_plans; ok=False events also carry violations/first)
    "plan_verify": ("stage", "ok"),
    # the static plan budgeter's per-statement verdict (engine.plan_budget;
    # analysis/budget.py): modeled peak vs the working-set budget, plus
    # peak_blocked_bytes/window_rows/nodes detail
    "plan_budget": ("verdict", "peak_bytes", "budget_bytes"),
    # the host-RSS watermark sampler pre-empted memory pressure mid-query
    # (report.py; shrinks the blocked-union window before the allocator
    # fails)
    "mem_watermark": ("rss_bytes", "watermark_bytes"),
    # one collective exchange executed under a device mesh
    # (exec._try_exchange_join hash-partitioned join / _try_dist_sort
    # samplesort): interconnect bytes moved (padded-capacity measure over
    # both all_to_all passes), partition (device) count, the received-row
    # skew ratio (max device / mean; 1.0 = perfectly balanced), and how
    # many capacity-overflow retries the step burned before it fit.
    # Optional: dur_ms (measured wall of the whole exchange step, retries
    # included — the critical-path exchange-wait cause) and per_device
    # (received-row counts per device — what names the straggler)
    "exchange": ("op", "partitions", "bytes_moved", "skew", "retries"),
    # a fact table could not row-shard over the session mesh (capacity not
    # divisible by the device count) and fell back to full replication
    # (session.Catalog._to_device) — loud by contract: the event feeds a
    # metric family and the entry flag arms the verifier's replicated-dim
    # rule. Optional: bytes (host-side table size now copied per device).
    "mesh_fallback": ("table", "n_dev", "cap"),
    # one out-of-core (spilled) operator execution (engine/spill.py +
    # exec's _spilled_join/_spilled_take/_spilled_distinct): host-pool
    # traffic for a partitioned hash join / external sort / spilling
    # distinct — bytes into/out of the pool, partition count, and how many
    # segments tiered down to the spill dir
    "spill": ("op", "partitions", "bytes_in", "bytes_out", "evictions"),
    # one lakehouse manifest publish attempt outcome (lakehouse/table.py
    # _commit): `attempts` counts OCC tries incl. rebases; successful
    # commits also carry `rebased`, losers carry `conflict`: true
    "lake_commit": ("table", "operation", "version", "attempts"),
    # one lakehouse vacuum (snapshot expiry + unreferenced-file delete):
    # files_leased counts files KEPT because a live reader lease covers
    # them — the vacuum safety contract made visible
    "lake_vacuum": ("table", "files_removed", "manifests_removed",
                    "files_leased"),
    # one parallel-ingest chunk committed through the lakehouse ledger
    # (transcode.py _ingest_chunks → table.ingest_chunk): decode_ms is
    # the Arrow decode of the chunk file, commit_ms covers stage+commit
    # (the commit-wait critical-path bucket). Optional: files (staged
    # file count), version, skipped: true (chunk already in the ledger
    # — the resume path's exactly-once skip)
    "ingest_chunk": ("table", "chunk", "rows", "decode_ms", "commit_ms"),
    # one zone-map pruning pass over a pinned lakehouse scan
    # (Session._prune_lake_scans): files_pruned of files_total were
    # excluded by the manifest's per-file stats; rows_bound is the
    # surviving-row upper bound handed to the budgeter (None when
    # nothing pruned)
    "scan_prune": ("table", "files_total", "files_pruned", "rows_bound",
                   "dur_ms"),
    # one fleet-catalog commit arbitration (lakehouse/catalog.py): outcome
    # is ok | conflict | fenced | unreachable | expired (a slow
    # coordinator refusing a publish past the client's deadline) |
    # rolled_back (coordinator WAL recovery). Optional: dur_ms, txid,
    # epoch — the cross-host half of lake_commit's story (a table-level
    # lake_commit may cover several catalog_commit attempts)
    "catalog_commit": ("table", "backend", "version", "outcome"),
    # one fleet-catalog lease/fence operation: op is acquire | renew |
    # release | sweep | writer_register | fence_bump. Optional: table,
    # version, epoch, fence, live_writers, removed
    "catalog_lease": ("op", "backend", "outcome"),
    # one serve-mode request outcome (nds_tpu/serve/service.py): status is
    # completed | failed | rejected | shed | draining, http_status the
    # wire answer. Optional: request_id, query, verdict (the admission
    # echo), rows, bytes, and per-request cache tallies
    # (exec_cache_hits/_lookups, plan_cache_hits/_lookups) that feed the
    # per-tenant hit rates on /statusz.
    "serve_request": ("tenant", "status", "dur_ms", "http_status"),
    # one router-edge request outcome (nds_tpu/serve/router.py): status is
    # completed | failed | rejected | shed | draining, http_status the
    # answer the CLIENT saw. Optional: request_id, replica (the upstream
    # that served it), verdict (cached/probed budget verdict that drove
    # the pick), stmt_class (select | dml), attempts (total upstream
    # forwards), retries, queue_ms (edge admission: verdict lookup +
    # replica pick), forward_ms (total upstream wire time), query — the
    # critical-path profiler folds queue_ms/forward_ms into the
    # router-queue / router-forward buckets.
    "route_request": ("tenant", "status", "dur_ms", "http_status"),
    # one router failover/shed retry decision (nds_tpu/serve/router.py):
    # reason is connect | midstream | shed | fault | upstream. Optional:
    # tenant, request_id, attempt, delay_ms
    "route_retry": ("replica", "reason"),
    # estimate-vs-actual cardinality feedback (analysis/feedback.py):
    # op "annotate"/"consume" (budget_plan's per-statement summary —
    # result applied | static, with mode/lookups/hits/overrides/verdict)
    # and "record" (one executed node's measured cardinality folded into
    # the FeedbackStore — result ok, with node/actual_rows and, when the
    # static estimate was annotated, est_rows + abs_log_err, the
    # |log(est/actual)| error sample `profile --accuracy` distributes).
    # op_span events on feedback-annotated nodes also carry node_fp /
    # est_rows / est_live_bytes / actual_rows / actual_bytes as optional
    # fields (est_bytes keeps its historical realized-bytes meaning)
    "plan_feedback": ("op", "result"),
    # liveness beacon from the per-query memory-sampler thread
    # (obs/memwatch.py, armed by report.py while a traced query runs):
    # a hung query keeps heartbeating, so the hang is visible live on
    # /statusz (heartbeat age + in-flight elapsed) and classifiable
    # post-hoc from the log tail. Interval: NDS_HEARTBEAT_INTERVAL_MS.
    # Optional: dev_bytes (per-device HBM sample list, device-source
    # runs — feeds the /statusz mesh section's high-water)
    "heartbeat": ("query", "elapsed_ms", "rss_bytes"),
    # runtime lock sanitizer (engine/lockdebug.py, engine.lock_debug):
    # one acquisition whose wait crossed engine.lock_contention_ms.
    # `lock` is the static model's name (ClassName.attr / relpath:NAME,
    # anchors/lock_order.golden), wait_ms the measured acquire wait
    "lock_contention": ("lock", "wait_ms"),
}

#: fields `Tracer.emit` stamps on EVERY event from the tracer's
#: TraceContext (alongside ts/kind/app). Readers treat them as optional
#: (pre-context logs lack them); call sites never pass them explicitly —
#: the `trace-event-schema` lint flags an explicit `trace_id=` kwarg on a
#: kind that does not declare it in EVENT_SCHEMA.
CONTEXT_FIELDS = ("trace_id",)

#: kinds kept in EVENT_SCHEMA for old-log readers but no longer emitted by
#: the current tree; the golden-sync test (tests/test_analysis.py) requires
#: every NON-deprecated kind to have a live emission site, and every
#: emitted kind to be in EVENT_SCHEMA
DEPRECATED_EVENT_KINDS = frozenset()


def resolve_trace_dir(conf: dict | None = None) -> str | None:
    """Trace directory from conf `engine.trace_dir`, else NDS_TRACE_DIR;
    None (tracing disabled) when neither is set."""
    v = None
    if conf:
        v = conf.get("engine.trace_dir")
    v = v or os.environ.get("NDS_TRACE_DIR")
    return str(v) if v else None


def resolve_kernel_trace(conf: dict | None = None) -> bool:
    """Per-kernel dispatch timing (conf `engine.trace_kernels`, env
    NDS_TRACE_KERNELS). Off by default: each traced kernel call blocks on
    its result, so this is a profiling mode, not a steady-state default."""
    v = None
    if conf:
        v = conf.get("engine.trace_kernels")
    if v is None:
        v = os.environ.get("NDS_TRACE_KERNELS")
    return str(v).lower() in ("1", "on", "true") if v is not None else False


def resolve_rotate_bytes(conf: dict | None = None) -> int:
    """Trace-segment rotation threshold in bytes (conf
    `engine.trace_rotate_bytes`, env NDS_TRACE_ROTATE_BYTES); 0 — the
    default — disables rotation (one `events-<appid>.jsonl` forever, the
    pre-rotation behavior). With a threshold, the tracer rolls to
    `events-<appid>.<seq>.jsonl` segments so long-running fleets can
    compact closed segments (`profile compact`) instead of growing one
    unbounded log."""
    v = None
    if conf:
        v = conf.get("engine.trace_rotate_bytes")
    if v is None:
        v = os.environ.get("NDS_TRACE_ROTATE_BYTES")
    try:
        return max(int(v), 0) if v else 0
    except (TypeError, ValueError):
        return 0


def default_app_id() -> str:
    """Unique per-tracer app id: pid + epoch second + random suffix (two
    thread-mode throughput streams in one process must not collide)."""
    return f"nds-tpu-{os.getpid()}-{int(time.time())}-{uuid.uuid4().hex[:6]}"


#: env var carrying a parent-minted trace context into a child process
TRACE_CONTEXT_ENV = "NDS_TRACE_CONTEXT"


class TraceContext:
    """Cross-process trace correlation: a `trace_id` (the whole-run or
    per-request correlation key `Tracer.emit` stamps on every event) plus
    the minting parent's trace_id.

    Propagation contract: a LAUNCHER mints one context per child
    (`ctx.child()`) and exports it (`ctx.export(env)`); the child's
    `tracer_from_conf` finds NDS_TRACE_CONTEXT and adopts the context
    VERBATIM — so the parent knows the exact trace_id the child's event
    files carry and folds them by trace_id, immune to pid recycling. A
    process with nothing in the environment mints a fresh root context."""

    __slots__ = ("trace_id", "parent")

    def __init__(self, trace_id: str, parent: str | None = None):
        self.trace_id = str(trace_id)
        self.parent = str(parent) if parent else None

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, parent={self.parent!r})"

    @classmethod
    def mint(cls, entry: str = "nds", parent: str | None = None):
        """A fresh context for an entry point (power, throughput,
        full_bench, a serve request, a DM function...)."""
        return cls(f"{entry}-{uuid.uuid4().hex[:16]}", parent=parent)

    def child(self, entry: str = "child") -> "TraceContext":
        """A context for a subprocess this process launches: fresh
        trace_id, parented to this one."""
        return TraceContext.mint(entry, parent=self.trace_id)

    # -- env carriage ----------------------------------------------------
    def to_env_value(self) -> str:
        return (
            f"{self.trace_id},{self.parent}" if self.parent
            else self.trace_id
        )

    @classmethod
    def from_env_value(cls, value: str):
        value = str(value).strip()
        if not value:
            return None
        bits = value.split(",", 1)
        return cls(bits[0], parent=bits[1] if len(bits) > 1 else None)

    def export(self, env: dict) -> dict:
        """Write this context into a subprocess environment dict (and
        return it, for call-site chaining)."""
        env[TRACE_CONTEXT_ENV] = self.to_env_value()
        return env


def resolve_trace_context(entry: str = "proc") -> TraceContext:
    """The process's trace context: adopt a parent-exported
    NDS_TRACE_CONTEXT verbatim, else mint a fresh root for `entry`."""
    ctx = TraceContext.from_env_value(
        os.environ.get(TRACE_CONTEXT_ENV, "")
    )
    return ctx if ctx is not None else TraceContext.mint(entry)


def current_context() -> TraceContext | None:
    """The thread-bound tracer's context (None unbound) — launchers that
    want to parent a child context to the running stream's reach it
    here."""
    t = current()
    return getattr(t, "context", None) if t is not None else None


class Tracer:
    """Append-only JSON-lines event writer (or an in-memory collector when
    `trace_dir` is None — the dev-tool mode tools/trace_query.py uses; or
    a sink-only forwarder with `collect=False` — the live-telemetry-
    without-a-trace-dir mode).

    Thread-safe: a lock serializes writes, and each event line is emitted
    with a single write() + flush so concurrent streams/threads sharing a
    tracer never interleave mid-line.

    Rotation: with `rotate_bytes` set the tracer rolls to a new segment
    (`events-<appid>.<seq>.jsonl`, seq 1..) once the current one reaches
    the threshold; every segment opens with its own `trace_meta` line so
    each file is independently discoverable/attributable. Segment 0 keeps
    the classic un-suffixed name, so unrotated runs look exactly as
    before. `obs.reader` reassembles chains in seq order.

    Lifecycle: `close()` is terminal — a late emit after close is a
    harness-ordering bug and becomes a NO-OP with a one-shot warning
    (historically it silently reopened the file and leaked the handle)."""

    def __init__(self, trace_dir: str | None = None, app_id: str | None = None,
                 kernel_spans: bool = False, sink=None, rotate_bytes: int = 0,
                 collect: bool | None = None, context=None, ring=None):
        self.app_id = app_id or default_app_id()
        self.trace_dir = trace_dir
        # opt-in per-kernel dispatch timing: the ops.kernels instrumentation
        # only fires when the thread-bound tracer carries this flag
        self.kernel_spans = kernel_spans
        # live-telemetry bridge (obs/metrics.py): every emitted event also
        # updates the sink's counters/status; None = no live metrics
        self.sink = sink
        # cross-process correlation: every emitted event is stamped with
        # this context's trace_id (adopted from NDS_TRACE_CONTEXT when a
        # launcher minted one for this process, else freshly minted)
        self.context = context or resolve_trace_context("tracer")
        # flight-recorder ring (obs/flight.py): every emitted event also
        # lands in the process-wide bounded ring so a failure bundle has
        # the last-N events even when nothing else is configured. Ring
        # append is one GIL-atomic deque op — emitters never block.
        if ring is None:
            from . import flight as obs_flight

            ring = obs_flight.recorder()
        self.ring = ring or None
        self.rotate_bytes = max(int(rotate_bytes or 0), 0)
        self.seq = 0  # nds-guarded-by: _lock
        self.path = self._segment_path(0) if trace_dir else None  # nds-guarded-by: _lock
        if collect is None:
            collect = trace_dir is None
        self.events: list[dict] | None = (  # nds-guarded-by: _lock
            [] if (trace_dir is None and collect) else None
        )
        self._fh = None  # nds-guarded-by: _lock
        self._lock = make_lock("Tracer._lock")
        self._broken = False  # nds-guarded-by: _lock
        self._closed = False  # nds-guarded-by: _lock
        self._close_warned = False  # nds-guarded-by: _lock
        self._seg_bytes = 0  # nds-guarded-by: _lock
        if trace_dir:
            # eager meta line: the file exists (and is discoverable by a
            # parent/orchestrator) even if the process dies before its
            # first real event. Carries the trace context (trace_id via
            # the central stamp, parent explicitly) so fold-in can match
            # this file to its LAUNCH RECORD instead of trusting the pid.
            self.emit(
                "trace_meta", pid=os.getpid(), version=__version__,
                **({"parent": self.context.parent}
                   if self.context.parent else {}),
            )

    def _segment_path(self, seq: int) -> str:
        if seq == 0:
            return os.path.join(self.trace_dir, f"events-{self.app_id}.jsonl")
        # zero-padded so chains stay scannable by eye; ordering itself is
        # parsed, not lexicographic (obs.reader.segment_key)
        return os.path.join(
            self.trace_dir, f"events-{self.app_id}.{seq:04d}.jsonl"
        )

    # ------------------------------------------------------------------
    def emit(self, kind: str, **fields):
        """Record one event. `ts`/`kind`/`app` are added here; `query` is
        added from the active faults.scope when the caller didn't pass it."""
        if self._closed:
            # emit-after-close: a harness loop closed this tracer before
            # some late worker finished. Dropping is correct (the reader
            # contract says a closed file is final); reopening would leak
            # the handle and resurrect a file a parent may already have
            # folded in.
            with self._lock:
                if not self._close_warned:
                    self._close_warned = True
                    print(
                        f"obs: tracer {self.app_id} got an emit({kind!r}) "
                        f"after close(); dropping this and later events "
                        f"(close tracers only after their last emitter)"
                    )
            return
        ev = {
            "ts": int(time.time() * 1000), "kind": kind, "app": self.app_id,
            "trace_id": self.context.trace_id,
        }
        if "query" not in fields:
            scope = faults.current_scope()
            if scope is not None:
                ev["query"] = scope
        ev.update(fields)  # an explicit trace_id (serve's per-request
        # forwarding tracer) overrides the stamped context here
        if self.sink is not None:
            try:
                self.sink.record(ev)
            except Exception:
                pass  # live telemetry must never take the benchmark down
        if self.ring is not None:
            self.ring.record(ev)  # one bounded deque append; never blocks
        if self.path is None and self.events is None:
            return  # sink-only / ring-only mode: nothing to persist
        # serialize outside the lock (sink-only mode skipped it above)
        line = json.dumps(ev, default=str) if self.path is not None else None
        with self._lock:
            if self._closed:
                return  # raced a concurrent close(): the unlocked check
                # above passed before close() took the lock — reopening
                # here would resurrect the leak this check exists to kill
            if self.events is not None:
                self.events.append(ev)
                return
            if self._broken:
                return
            try:
                if self._fh is None:
                    parent = os.path.dirname(self.path)
                    # lazy open under _lock is the design: this lock
                    # exists to serialize exactly this segment handle,
                    # the makedirs/open pair runs once per segment, and
                    # emit serialized the payload before taking the lock.
                    if parent:
                        os.makedirs(parent, exist_ok=True)  # nds-lint: disable=blocking-under-lock
                    self._fh = open(self.path, "a", encoding="utf-8")  # nds-lint: disable=blocking-under-lock
                    self._seg_bytes = os.fstat(self._fh.fileno()).st_size
                data = line + "\n"
                self._fh.write(data)
                self._fh.flush()
                if self.rotate_bytes:
                    # byte accounting (an extra encode per line) only when
                    # rotation can actually consume it
                    self._seg_bytes += len(data.encode("utf-8"))
                    if self._seg_bytes >= self.rotate_bytes:
                        self._rotate_locked()
            except OSError as exc:
                # observability must never take the benchmark down: an
                # unwritable trace dir disables this tracer, loudly, once
                self._broken = True
                print(f"obs: disabling tracer ({self.path}: {exc})")

    def _rotate_locked(self):
        """Roll to the next segment (caller holds the lock). The new
        segment opens with its own trace_meta line (carrying `seq`) so a
        segment file found alone is still attributable to its process."""
        self._fh.close()
        self.seq += 1
        self.path = self._segment_path(self.seq)
        self._fh = open(self.path, "a", encoding="utf-8")
        meta = json.dumps({
            "ts": int(time.time() * 1000), "kind": "trace_meta",
            "app": self.app_id, "trace_id": self.context.trace_id,
            "pid": os.getpid(),
            "version": __version__, "seq": self.seq,
            **({"parent": self.context.parent}
               if self.context.parent else {}),
        })
        self._fh.write(meta + "\n")
        self._fh.flush()
        self._seg_bytes = len(meta.encode("utf-8")) + 1

    def close(self):
        """Terminal: flush + release the file handle and refuse later
        emits (see class docstring). Idempotent."""
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None  # nds-guarded-by: _lock


def tracer_from_conf(conf: dict | None = None, app_id: str | None = None,
                     context: TraceContext | None = None):
    """A Tracer for the configured observability shape.

    Four live shapes: a trace dir gives the classic file tracer; a
    metrics port alone gives a SINK-ONLY tracer (no file, no in-memory
    list — emission sites fire so the live registry stays hot, nothing is
    persisted); both give a file tracer that also feeds the sink; and
    with NEITHER configured the flight recorder keeps a RING-ONLY tracer
    (events feed the process-wide bounded ring so failures always leave a
    bundle). Only `engine.flight_recorder: off` / NDS_FLIGHT_RECORDER=off
    returns None — the historical fully-disabled zero-cost state.

    `context`: an explicit TraceContext for this tracer; default adopts
    NDS_TRACE_CONTEXT (a launcher minted one for this process) or mints a
    fresh root."""
    d = resolve_trace_dir(conf)
    # lazy: obs.metrics imports EVENT_SCHEMA from this module
    from . import flight as obs_flight
    from . import metrics as obs_metrics

    sink = obs_metrics.maybe_serve(conf)
    ring = obs_flight.recorder(conf)
    if context is None:
        context = resolve_trace_context("session")
    if not d:
        if sink is None and ring is None:
            return None
        return Tracer(
            None, app_id=app_id, kernel_spans=resolve_kernel_trace(conf),
            sink=sink, collect=False, context=context, ring=ring or False,
        )
    return Tracer(
        d, app_id=app_id, kernel_spans=resolve_kernel_trace(conf),
        sink=sink, rotate_bytes=resolve_rotate_bytes(conf),
        context=context, ring=ring or False,
    )


# ---------------------------------------------------------------------------
# thread-local binding: layers without a Session in hand (faults, io/fs)
# reach the right stream's tracer through `current()`
# ---------------------------------------------------------------------------

_tls = threading.local()


class bind:
    """Context manager binding a tracer (or None: no-op) to this thread so
    session-less layers (fault registry, fs retries) can emit into the
    stream that is actually running. Harness loops bind their session's
    tracer around query execution; BenchReport re-binds inside its watchdog
    worker thread (thread-locals don't inherit)."""

    def __init__(self, tracer: Tracer | None):
        self.tracer = tracer

    def __enter__(self):
        self.prev = getattr(_tls, "tracer", None)
        _tls.tracer = self.tracer
        return self.tracer

    def __exit__(self, *exc):
        _tls.tracer = self.prev
        return False


def current() -> Tracer | None:
    """The tracer bound to this thread, or None (events dropped)."""
    return getattr(_tls, "tracer", None)
