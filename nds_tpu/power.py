"""Power Run driver: execute a query stream sequentially with full reporting.

TPU-native counterpart of the reference Power Run (reference:
nds/nds_power.py:50-77 stream parsing, :79-106 table setup, :125-135 per-query
execution, :184-299 the timed loop + CSV time log). The engine session
replaces the SparkSession; per-query JSON summaries and the time-log format
are kept field-for-field compatible (nds/PysparkBenchReport.py:58-119).
"""

from __future__ import annotations

import csv
import os
import time
from collections import OrderedDict

from . import faults
from .check import check_json_summary_folder, check_query_subset_exists
from .io.fs import fs_open, fs_open_atomic
from .datagen.query_streams import split_special_query
from .engine.session import Session
from .report import BenchReport
from .schema import get_schemas


def gen_sql_from_stream(query_stream_file_path: str) -> "OrderedDict[str, str]":
    """Split a generated stream file into {query_name: sql} on the
    `-- start query N in stream S using template queryK.tpl` markers.
    Two-statement entries (templates 14/23/24/39) become `_part1`/`_part2`."""
    with fs_open(query_stream_file_path) as f:
        stream = f.read()
    queries = OrderedDict()
    for q in stream.split("-- start")[1:]:
        name = q[q.find("template") + 9 : q.find(".tpl")]
        parts = q.split(";")
        if len(parts) < 2:
            # a stream entry with no statement terminator would otherwise
            # surface as a bare IndexError from deep inside the split
            raise ValueError(
                f"malformed stream file {query_stream_file_path}: entry "
                f"{name or q.splitlines()[0].strip()!r} has no ';'-terminated "
                f"statement"
            )
        # a second statement before the end marker => two-part template
        if "select" in parts[1]:
            part_1, part_2 = split_special_query(q)
            queries[name + "_part1"] = "-- start" + part_1
            queries[name + "_part2"] = "-- start" + part_2
        else:
            queries[name] = "-- start" + q
    return queries


def get_query_subset(query_dict, subset):
    """Select a run subset (reference: nds/nds_power.py:176-181)."""
    check_query_subset_exists(query_dict, subset)
    return OrderedDict((k, query_dict[k]) for k in subset)


def setup_tables(session, input_prefix, input_format, use_decimal, execution_time_list, app_id):
    """Register every source table on the session, timing each registration
    (reference analogue: per-table temp-view creation, nds/nds_power.py:79-106).
    Elapsed times use the monotonic clock (an NTP step mid-setup must not
    corrupt a duration); the CSV rows carry durations only, so the epoch
    timestamp contract is untouched."""
    import glob

    schemas = get_schemas(use_decimal)
    for table_name, schema in schemas.items():
        start = time.perf_counter()
        table_path = os.path.join(input_prefix, table_name)
        if input_format == "csv":
            # raw generator output (pipe-delimited .dat chunks) vs a
            # transcoded csv warehouse (comma-delimited part files)
            if glob.glob(os.path.join(table_path, "*.dat")) or os.path.isfile(table_path):
                session.register_csv_dir(table_name, table_path, schema)
            else:
                session.register_csv_warehouse(table_name, table_path, schema)
        elif input_format == "parquet":
            session.register_parquet(table_name, table_path, schema)
        elif input_format == "orc":
            session.register_orc(table_name, table_path, schema)
        elif input_format == "lakehouse":
            session.register_lakehouse(table_name, table_path, schema)
        else:
            raise ValueError(f"unsupported input format {input_format}")
        dur_ms = int((time.perf_counter() - start) * 1000)
        print(f"====== Creating TempView for table {table_name} ======")
        print(f"Time taken: {dur_ms} millis for table {table_name}")
        execution_time_list.append(
            (app_id, f"CreateTempView {table_name}", dur_ms)
        )
    return execution_time_list


def ensure_valid_column_names(arrow_table):
    """Sanitize result column names before writing: invalid characters become
    underscores and duplicates get a positional suffix (reference:
    nds/nds_power.py:137-174 — parquet writers reject ` ,;{}()\\n\\t=`)."""
    import re

    invalid = re.compile(r"[ ,;{}()\n\t=]")
    names, seen = [], {}
    for n in arrow_table.column_names:
        clean = invalid.sub("_", n)
        if clean in seen:
            seen[clean] += 1
            clean = f"{clean}_{seen[clean]}"
        else:
            seen[clean] = 0
        names.append(clean)
    return arrow_table.rename_columns(names)


def run_one_query(session, query, query_name, output_path, output_format):
    """Execute one stream entry; collect to host, or write for validation
    (reference: nds/nds_power.py:125-135)."""
    with faults.scope(query_name):
        # primary per-query injection site (oom:<query>/hang:<query>/...);
        # sits inside the BenchReport attempt so injected faults walk the
        # same classification + ladder a real failure would
        faults.maybe_fire(query_name)
        result = session.run_script(query)
        if result is None:
            return
        if not output_path:
            result.collect()
        else:
            dest = os.path.join(output_path, query_name)
            result.write(dest, output_format, transform=ensure_valid_column_names)


def load_properties(filename: str) -> dict:
    props = {}
    with fs_open(filename) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, value = line.partition("=")
            props[name.strip()] = value.strip()
    return props


def run_query_stream(
    input_prefix,
    property_file,
    query_dict,
    time_log_output_path,
    extra_time_log_output_path=None,
    sub_queries=None,
    input_format="parquet",
    use_decimal=True,
    output_path=None,
    output_format="parquet",
    json_summary_folder=None,
    keep_session=False,
    mesh_devices=None,
    start_gate=None,
    query_timeout=None,
):
    """Run the stream sequentially with per-query timing and reports.

    Mirrors the reference loop (nds/nds_power.py:184-299): session build with
    property-file conf, table setup, per-query BenchReport with
    Failed-and-continue semantics, CSV time log, optional extra time log copy.
    Returns the session (so callers like the throughput driver can reuse it).
    """
    execution_time_list = []
    total_time_start = time.time()  # epoch: app-id stamp only
    total_start_mono = time.perf_counter()  # elapsed measurements
    app_name = (
        "NDS - " + next(iter(query_dict)) if len(query_dict) == 1 else "NDS - Power Run"
    )
    conf = {"app.name": app_name}
    if property_file:
        conf.update(load_properties(property_file))
    if query_timeout is not None:
        # CLI tier wins over property file (an explicit 0 DISABLES a
        # property-file watchdog); BenchReport reads this conf key
        # (falling back to NDS_QUERY_TIMEOUT) for its watchdog budget
        conf["engine.query_timeout"] = query_timeout
    check_json_summary_folder(json_summary_folder)
    mesh = None
    if mesh_devices:
        from .parallel.dist import make_mesh

        mesh = make_mesh(mesh_devices)
        conf["engine.mesh_devices"] = mesh_devices
    session = Session(use_decimal=use_decimal, conf=conf, mesh=mesh)
    app_id = f"nds-tpu-{os.getpid()}-{int(total_time_start)}"
    try:
        return _run_query_stream_body(
            session, app_id, total_start_mono, input_prefix, property_file,
            query_dict, time_log_output_path, extra_time_log_output_path,
            sub_queries, input_format, use_decimal, output_path,
            output_format, json_summary_folder, keep_session, start_gate,
            execution_time_list,
        )
    finally:
        # the stream is this tracer's ONLY emitter: closing here (success
        # or crash) releases the handle and flushes the final line; a late
        # emit after this point is a harness bug the tracer now drops
        # loudly instead of silently reopening the file (obs/trace.py)
        if not keep_session and session.tracer is not None:
            session.tracer.close()


def _run_query_stream_body(
    session, app_id, total_start_mono, input_prefix, property_file,
    query_dict, time_log_output_path, extra_time_log_output_path,
    sub_queries, input_format, use_decimal, output_path, output_format,
    json_summary_folder, keep_session, start_gate, execution_time_list,
):
    execution_time_list = setup_tables(
        session, input_prefix, input_format, use_decimal, execution_time_list, app_id
    )
    if sub_queries:
        query_dict = get_query_subset(query_dict, sub_queries)
    if start_gate is not None:
        # concurrent-stream rendezvous (throughput driver): every stream
        # finishes setup before any stream's Power clock starts, and the
        # gate's shared release timestamp becomes the stream's start, so
        # the [start, end] windows overlap by construction rather than by
        # scheduling luck on a loaded host
        gate_t = start_gate()
        power_start = int(gate_t) if gate_t is not None else int(time.time())
    else:
        power_start = int(time.time())
    # epoch Power Start/End rows are the CSV time-log contract (Ttt reads
    # them across streams); the ELAPSED figures are monotonic so a clock
    # step mid-run cannot corrupt Tpower
    power_start_mono = time.perf_counter()
    # bind this stream's tracer to the driver thread: session-less layers
    # (fault registry, fs retries) emit into the right stream's event file
    # (BenchReport re-binds inside its watchdog worker thread itself)
    from .obs import trace as obs_trace

    with obs_trace.bind(session.tracer):
        for query_name, q_content in query_dict.items():
            print(f"====== Run {query_name} ======")
            q_report = BenchReport(session)
            summary = q_report.report_on(
                run_one_query, session, q_content, query_name, output_path,
                output_format, retry_oom=True,  # read-only: idempotent
                name=query_name,
            )
            print(f"Time taken: {summary['queryTimes']} millis for {query_name}")
            execution_time_list.append((app_id, query_name, summary["queryTimes"][0]))
            if json_summary_folder:
                if property_file:
                    summary_prefix = os.path.join(
                        json_summary_folder, os.path.basename(property_file).split(".")[0]
                    )
                else:
                    summary_prefix = os.path.join(json_summary_folder, "")
                q_report.write_summary(query_name, prefix=summary_prefix)
    power_end = int(time.time())
    power_elapse = int((time.perf_counter() - power_start_mono) * 1000)
    total_elapse = int((time.perf_counter() - total_start_mono) * 1000)
    print(f"====== Power Test Time: {power_elapse} milliseconds ======")
    print(f"====== Total Time: {total_elapse} milliseconds ======")
    execution_time_list.append((app_id, "Power Start Time", power_start))
    execution_time_list.append((app_id, "Power End Time", power_end))
    execution_time_list.append((app_id, "Power Test Time", power_elapse))
    execution_time_list.append((app_id, "Total Time", total_elapse))

    header = ["application_id", "query", "time/milliseconds"]
    print(header)
    for row in execution_time_list:
        print(row)
    if time_log_output_path:
        # atomic: full_bench resume re-parses this log, so a crash mid-write
        # must leave either no log or a complete one, never a torn file
        with fs_open_atomic(time_log_output_path, "w", encoding="UTF8", newline="") as f:
            writer = csv.writer(f)
            writer.writerow(header)
            writer.writerows(execution_time_list)
    if extra_time_log_output_path:
        # reference writes this via Spark so it can land on cloud storage;
        # our IO layer is fs-agnostic, a plain copy keeps the contract
        with fs_open_atomic(extra_time_log_output_path, "w", encoding="UTF8", newline="") as f:
            writer = csv.writer(f)
            writer.writerow(header)
            writer.writerows(execution_time_list)
    return session if keep_session else None
