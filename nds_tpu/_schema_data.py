"""TPC-DS table schema data (spec v3.2.0 facts).
Column names/types follow the TPC-DS specification; parity target is the
reference schema registry (reference: nds/nds_schema.py:49-710). Each entry is
one whitespace-separated line per column: "name dtype [!]" where "!" marks
non-nullable. Generated from spec facts; formatting is ours.
"""

SOURCE_TABLES = {
'dbgen_version': """\
    dv_version       varchar(16)
    dv_create_date   date
    dv_create_time   char(20)
    dv_cmdline_args  varchar(200)
""",
'customer_address': """\
    ca_address_sk     int32  !
    ca_address_id     char(16)  !
    ca_street_number  char(10)
    ca_street_name    varchar(60)
    ca_street_type    char(15)
    ca_suite_number   char(10)
    ca_city           varchar(60)
    ca_county         varchar(30)
    ca_state          char(2)
    ca_zip            char(10)
    ca_country        varchar(20)
    ca_gmt_offset     decimal(5,2)
    ca_location_type  char(20)
""",
'customer_demographics': """\
    cd_demo_sk             int32  !
    cd_gender              char(1)
    cd_marital_status      char(1)
    cd_education_status    char(20)
    cd_purchase_estimate   int32
    cd_credit_rating       char(10)
    cd_dep_count           int32
    cd_dep_employed_count  int32
    cd_dep_college_count   int32
""",
'date_dim': """\
    d_date_sk            int32  !
    d_date_id            char(16)  !
    d_date               date
    d_month_seq          int32
    d_week_seq           int32
    d_quarter_seq        int32
    d_year               int32
    d_dow                int32
    d_moy                int32
    d_dom                int32
    d_qoy                int32
    d_fy_year            int32
    d_fy_quarter_seq     int32
    d_fy_week_seq        int32
    d_day_name           char(9)
    d_quarter_name       char(6)
    d_holiday            char(1)
    d_weekend            char(1)
    d_following_holiday  char(1)
    d_first_dom          int32
    d_last_dom           int32
    d_same_day_ly        int32
    d_same_day_lq        int32
    d_current_day        char(1)
    d_current_week       char(1)
    d_current_month      char(1)
    d_current_quarter    char(1)
    d_current_year       char(1)
""",
'warehouse': """\
    w_warehouse_sk     int32  !
    w_warehouse_id     char(16)  !
    w_warehouse_name   varchar(20)
    w_warehouse_sq_ft  int32
    w_street_number    char(10)
    w_street_name      varchar(60)
    w_street_type      char(15)
    w_suite_number     char(10)
    w_city             varchar(60)
    w_county           varchar(30)
    w_state            char(2)
    w_zip              char(10)
    w_country          varchar(20)
    w_gmt_offset       decimal(5,2)
""",
'ship_mode': """\
    sm_ship_mode_sk  int32  !
    sm_ship_mode_id  char(16)  !
    sm_type          char(30)
    sm_code          char(10)
    sm_carrier       char(20)
    sm_contract      char(20)
""",
'time_dim': """\
    t_time_sk    int32  !
    t_time_id    char(16)  !
    t_time       int32
    t_hour       int32
    t_minute     int32
    t_second     int32
    t_am_pm      char(2)
    t_shift      char(20)
    t_sub_shift  char(20)
    t_meal_time  char(20)
""",
'reason': """\
    r_reason_sk    int32  !
    r_reason_id    char(16)  !
    r_reason_desc  char(100)
""",
'income_band': """\
    ib_income_band_sk  int32  !
    ib_lower_bound     int32
    ib_upper_bound     int32
""",
'item': """\
    i_item_sk         int32  !
    i_item_id         char(16)  !
    i_rec_start_date  date
    i_rec_end_date    date
    i_item_desc       varchar(200)
    i_current_price   decimal(7,2)
    i_wholesale_cost  decimal(7,2)
    i_brand_id        int32
    i_brand           char(50)
    i_class_id        int32
    i_class           char(50)
    i_category_id     int32
    i_category        char(50)
    i_manufact_id     int32
    i_manufact        char(50)
    i_size            char(20)
    i_formulation     char(20)
    i_color           char(20)
    i_units           char(10)
    i_container       char(10)
    i_manager_id      int32
    i_product_name    char(50)
""",
'store': """\
    s_store_sk          int32  !
    s_store_id          char(16)  !
    s_rec_start_date    date
    s_rec_end_date      date
    s_closed_date_sk    int32
    s_store_name        varchar(50)
    s_number_employees  int32
    s_floor_space       int32
    s_hours             char(20)
    s_manager           varchar(40)
    s_market_id         int32
    s_geography_class   varchar(100)
    s_market_desc       varchar(100)
    s_market_manager    varchar(40)
    s_division_id       int32
    s_division_name     varchar(50)
    s_company_id        int32
    s_company_name      varchar(50)
    s_street_number     varchar(10)
    s_street_name       varchar(60)
    s_street_type       char(15)
    s_suite_number      char(10)
    s_city              varchar(60)
    s_county            varchar(30)
    s_state             char(2)
    s_zip               char(10)
    s_country           varchar(20)
    s_gmt_offset        decimal(5,2)
    s_tax_precentage    decimal(5,2)
""",
'call_center': """\
    cc_call_center_sk  int32  !
    cc_call_center_id  char(16)  !
    cc_rec_start_date  date
    cc_rec_end_date    date
    cc_closed_date_sk  int32
    cc_open_date_sk    int32
    cc_name            varchar(50)
    cc_class           varchar(50)
    cc_employees       int32
    cc_sq_ft           int32
    cc_hours           char(20)
    cc_manager         varchar(40)
    cc_mkt_id          int32
    cc_mkt_class       char(50)
    cc_mkt_desc        varchar(100)
    cc_market_manager  varchar(40)
    cc_division        int32
    cc_division_name   varchar(50)
    cc_company         int32
    cc_company_name    char(50)
    cc_street_number   char(10)
    cc_street_name     varchar(60)
    cc_street_type     char(15)
    cc_suite_number    char(10)
    cc_city            varchar(60)
    cc_county          varchar(30)
    cc_state           char(2)
    cc_zip             char(10)
    cc_country         varchar(20)
    cc_gmt_offset      decimal(5,2)
    cc_tax_percentage  decimal(5,2)
""",
'customer': """\
    c_customer_sk           int32  !
    c_customer_id           char(16)  !
    c_current_cdemo_sk      int32
    c_current_hdemo_sk      int32
    c_current_addr_sk       int32
    c_first_shipto_date_sk  int32
    c_first_sales_date_sk   int32
    c_salutation            char(10)
    c_first_name            char(20)
    c_last_name             char(30)
    c_preferred_cust_flag   char(1)
    c_birth_day             int32
    c_birth_month           int32
    c_birth_year            int32
    c_birth_country         varchar(20)
    c_login                 char(13)
    c_email_address         char(50)
    c_last_review_date_sk   char(10)
""",
'web_site': """\
    web_site_sk         int32  !
    web_site_id         char(16)  !
    web_rec_start_date  date
    web_rec_end_date    date
    web_name            varchar(50)
    web_open_date_sk    int32
    web_close_date_sk   int32
    web_class           varchar(50)
    web_manager         varchar(40)
    web_mkt_id          int32
    web_mkt_class       varchar(50)
    web_mkt_desc        varchar(100)
    web_market_manager  varchar(40)
    web_company_id      int32
    web_company_name    char(50)
    web_street_number   char(10)
    web_street_name     varchar(60)
    web_street_type     char(15)
    web_suite_number    char(10)
    web_city            varchar(60)
    web_county          varchar(30)
    web_state           char(2)
    web_zip             char(10)
    web_country         varchar(20)
    web_gmt_offset      decimal(5,2)
    web_tax_percentage  decimal(5,2)
""",
'store_returns': """\
    sr_returned_date_sk    int32
    sr_return_time_sk      int32
    sr_item_sk             int32  !
    sr_customer_sk         int32
    sr_cdemo_sk            int32
    sr_hdemo_sk            int32
    sr_addr_sk             int32
    sr_store_sk            int32
    sr_reason_sk           int32
    sr_ticket_number       int64  !
    sr_return_quantity     int32
    sr_return_amt          decimal(7,2)
    sr_return_tax          decimal(7,2)
    sr_return_amt_inc_tax  decimal(7,2)
    sr_fee                 decimal(7,2)
    sr_return_ship_cost    decimal(7,2)
    sr_refunded_cash       decimal(7,2)
    sr_reversed_charge     decimal(7,2)
    sr_store_credit        decimal(7,2)
    sr_net_loss            decimal(7,2)
""",
'household_demographics': """\
    hd_demo_sk         int32  !
    hd_income_band_sk  int32
    hd_buy_potential   char(15)
    hd_dep_count       int32
    hd_vehicle_count   int32
""",
'web_page': """\
    wp_web_page_sk       int32  !
    wp_web_page_id       char(16)  !
    wp_rec_start_date    date
    wp_rec_end_date      date
    wp_creation_date_sk  int32
    wp_access_date_sk    int32
    wp_autogen_flag      char(1)
    wp_customer_sk       int32
    wp_url               varchar(100)
    wp_type              char(50)
    wp_char_count        int32
    wp_link_count        int32
    wp_image_count       int32
    wp_max_ad_count      int32
""",
'promotion': """\
    p_promo_sk         int32  !
    p_promo_id         char(16)  !
    p_start_date_sk    int32
    p_end_date_sk      int32
    p_item_sk          int32
    p_cost             decimal(15,2)
    p_response_target  int32
    p_promo_name       char(50)
    p_channel_dmail    char(1)
    p_channel_email    char(1)
    p_channel_catalog  char(1)
    p_channel_tv       char(1)
    p_channel_radio    char(1)
    p_channel_press    char(1)
    p_channel_event    char(1)
    p_channel_demo     char(1)
    p_channel_details  varchar(100)
    p_purpose          char(15)
    p_discount_active  char(1)
""",
'catalog_page': """\
    cp_catalog_page_sk      int32  !
    cp_catalog_page_id      char(16)  !
    cp_start_date_sk        int32
    cp_end_date_sk          int32
    cp_department           varchar(50)
    cp_catalog_number       int32
    cp_catalog_page_number  int32
    cp_description          varchar(100)
    cp_type                 varchar(100)
""",
'inventory': """\
    inv_date_sk           int32  !
    inv_item_sk           int32  !
    inv_warehouse_sk      int32  !
    inv_quantity_on_hand  int32
""",
'catalog_returns': """\
    cr_returned_date_sk       int32
    cr_returned_time_sk       int32
    cr_item_sk                int32  !
    cr_refunded_customer_sk   int32
    cr_refunded_cdemo_sk      int32
    cr_refunded_hdemo_sk      int32
    cr_refunded_addr_sk       int32
    cr_returning_customer_sk  int32
    cr_returning_cdemo_sk     int32
    cr_returning_hdemo_sk     int32
    cr_returning_addr_sk      int32
    cr_call_center_sk         int32
    cr_catalog_page_sk        int32
    cr_ship_mode_sk           int32
    cr_warehouse_sk           int32
    cr_reason_sk              int32
    cr_order_number           int32  !
    cr_return_quantity        int32
    cr_return_amount          decimal(7,2)
    cr_return_tax             decimal(7,2)
    cr_return_amt_inc_tax     decimal(7,2)
    cr_fee                    decimal(7,2)
    cr_return_ship_cost       decimal(7,2)
    cr_refunded_cash          decimal(7,2)
    cr_reversed_charge        decimal(7,2)
    cr_store_credit           decimal(7,2)
    cr_net_loss               decimal(7,2)
""",
'web_returns': """\
    wr_returned_date_sk       int32
    wr_returned_time_sk       int32
    wr_item_sk                int32  !
    wr_refunded_customer_sk   int32
    wr_refunded_cdemo_sk      int32
    wr_refunded_hdemo_sk      int32
    wr_refunded_addr_sk       int32
    wr_returning_customer_sk  int32
    wr_returning_cdemo_sk     int32
    wr_returning_hdemo_sk     int32
    wr_returning_addr_sk      int32
    wr_web_page_sk            int32
    wr_reason_sk              int32
    wr_order_number           int32  !
    wr_return_quantity        int32
    wr_return_amt             decimal(7,2)
    wr_return_tax             decimal(7,2)
    wr_return_amt_inc_tax     decimal(7,2)
    wr_fee                    decimal(7,2)
    wr_return_ship_cost       decimal(7,2)
    wr_refunded_cash          decimal(7,2)
    wr_reversed_charge        decimal(7,2)
    wr_account_credit         decimal(7,2)
    wr_net_loss               decimal(7,2)
""",
'web_sales': """\
    ws_sold_date_sk           int32
    ws_sold_time_sk           int32
    ws_ship_date_sk           int32
    ws_item_sk                int32  !
    ws_bill_customer_sk       int32
    ws_bill_cdemo_sk          int32
    ws_bill_hdemo_sk          int32
    ws_bill_addr_sk           int32
    ws_ship_customer_sk       int32
    ws_ship_cdemo_sk          int32
    ws_ship_hdemo_sk          int32
    ws_ship_addr_sk           int32
    ws_web_page_sk            int32
    ws_web_site_sk            int32
    ws_ship_mode_sk           int32
    ws_warehouse_sk           int32
    ws_promo_sk               int32
    ws_order_number           int32  !
    ws_quantity               int32
    ws_wholesale_cost         decimal(7,2)
    ws_list_price             decimal(7,2)
    ws_sales_price            decimal(7,2)
    ws_ext_discount_amt       decimal(7,2)
    ws_ext_sales_price        decimal(7,2)
    ws_ext_wholesale_cost     decimal(7,2)
    ws_ext_list_price         decimal(7,2)
    ws_ext_tax                decimal(7,2)
    ws_coupon_amt             decimal(7,2)
    ws_ext_ship_cost          decimal(7,2)
    ws_net_paid               decimal(7,2)
    ws_net_paid_inc_tax       decimal(7,2)
    ws_net_paid_inc_ship      decimal(7,2)
    ws_net_paid_inc_ship_tax  decimal(7,2)
    ws_net_profit             decimal(7,2)
""",
'catalog_sales': """\
    cs_sold_date_sk           int32
    cs_sold_time_sk           int32
    cs_ship_date_sk           int32
    cs_bill_customer_sk       int32
    cs_bill_cdemo_sk          int32
    cs_bill_hdemo_sk          int32
    cs_bill_addr_sk           int32
    cs_ship_customer_sk       int32
    cs_ship_cdemo_sk          int32
    cs_ship_hdemo_sk          int32
    cs_ship_addr_sk           int32
    cs_call_center_sk         int32
    cs_catalog_page_sk        int32
    cs_ship_mode_sk           int32
    cs_warehouse_sk           int32
    cs_item_sk                int32  !
    cs_promo_sk               int32
    cs_order_number           int32  !
    cs_quantity               int32
    cs_wholesale_cost         decimal(7,2)
    cs_list_price             decimal(7,2)
    cs_sales_price            decimal(7,2)
    cs_ext_discount_amt       decimal(7,2)
    cs_ext_sales_price        decimal(7,2)
    cs_ext_wholesale_cost     decimal(7,2)
    cs_ext_list_price         decimal(7,2)
    cs_ext_tax                decimal(7,2)
    cs_coupon_amt             decimal(7,2)
    cs_ext_ship_cost          decimal(7,2)
    cs_net_paid               decimal(7,2)
    cs_net_paid_inc_tax       decimal(7,2)
    cs_net_paid_inc_ship      decimal(7,2)
    cs_net_paid_inc_ship_tax  decimal(7,2)
    cs_net_profit             decimal(7,2)
""",
'store_sales': """\
    ss_sold_date_sk        int32
    ss_sold_time_sk        int32
    ss_item_sk             int32  !
    ss_customer_sk         int32
    ss_cdemo_sk            int32
    ss_hdemo_sk            int32
    ss_addr_sk             int32
    ss_store_sk            int32
    ss_promo_sk            int32
    ss_ticket_number       int32  !
    ss_quantity            int32
    ss_wholesale_cost      decimal(7,2)
    ss_list_price          decimal(7,2)
    ss_sales_price         decimal(7,2)
    ss_ext_discount_amt    decimal(7,2)
    ss_ext_sales_price     decimal(7,2)
    ss_ext_wholesale_cost  decimal(7,2)
    ss_ext_list_price      decimal(7,2)
    ss_ext_tax             decimal(7,2)
    ss_coupon_amt          decimal(7,2)
    ss_net_paid            decimal(7,2)
    ss_net_paid_inc_tax    decimal(7,2)
    ss_net_profit          decimal(7,2)
""",
}

MAINTENANCE_TABLES = {
's_purchase_lineitem': """\
    plin_purchase_id   int32  !
    plin_line_number   int32  !
    plin_item_id       char(16)
    plin_promotion_id  char(16)
    plin_quantity      int32
    plin_sale_price    decimal(7,2)
    plin_coupon_amt    decimal(7,2)
    plin_comment       varchar(100)
""",
's_purchase': """\
    purc_purchase_id    int32  !
    purc_store_id       char(16)
    purc_customer_id    char(16)
    purc_purchase_date  char(10)
    purc_purchase_time  int32
    purc_register_id    int32
    purc_clerk_id       int32
    purc_comment        char(100)
""",
's_catalog_order': """\
    cord_order_id          int32  !
    cord_bill_customer_id  char(16)
    cord_ship_customer_id  char(16)
    cord_order_date        char(10)
    cord_order_time        int32
    cord_ship_mode_id      char(16)
    cord_call_center_id    char(16)
    cord_order_comments    varchar(100)
""",
's_web_order': """\
    word_order_id          int32  !
    word_bill_customer_id  char(16)
    word_ship_customer_id  char(16)
    word_order_date        char(10)
    word_order_time        int32
    word_ship_mode_id      char(16)
    word_web_site_id       char(16)
    word_order_comments    char(100)
""",
's_catalog_order_lineitem': """\
    clin_order_id             int32  !
    clin_line_number          int32  !
    clin_item_id              char(16)
    clin_promotion_id         char(16)
    clin_quantity             int32
    clin_sales_price          decimal(7,2)
    clin_coupon_amt           decimal(7,2)
    clin_warehouse_id         char(16)
    clin_ship_date            char(10)
    clin_catalog_number       int32
    clin_catalog_page_number  int32
    clin_ship_cost            decimal(7,2)
""",
's_web_order_lineitem': """\
    wlin_order_id      int32  !
    wlin_line_number   int32  !
    wlin_item_id       char(16)
    wlin_promotion_id  char(16)
    wlin_quantity      int32
    wlin_sales_price   decimal(7,2)
    wlin_coupon_amt    decimal(7,2)
    wlin_warehouse_id  char(16)
    wlin_ship_date     char(10)
    wlin_ship_cost     decimal(7,2)
    wlin_web_page_id   char(16)
""",
's_store_returns': """\
    sret_store_id          char(16)
    sret_purchase_id       char(16)  !
    sret_line_number       int32  !
    sret_item_id           char(16)  !
    sret_customer_id       char(16)
    sret_return_date       char(10)
    sret_return_time       char(10)
    sret_ticket_number     int64
    sret_return_qty        int32
    sret_return_amt        decimal(7,2)
    sret_return_tax        decimal(7,2)
    sret_return_fee        decimal(7,2)
    sret_return_ship_cost  decimal(7,2)
    sret_refunded_cash     decimal(7,2)
    sret_reversed_charge   decimal(7,2)
    sret_store_credit      decimal(7,2)
    sret_reason_id         char(16)
""",
's_catalog_returns': """\
    cret_call_center_id      char(16)
    cret_order_id            int32  !
    cret_line_number         int32  !
    cret_item_id             char(16)  !
    cret_return_customer_id  char(16)
    cret_refund_customer_id  char(16)
    cret_return_date         char(10)
    cret_return_time         char(10)
    cret_return_qty          int32
    cret_return_amt          decimal(7,2)
    cret_return_tax          decimal(7,2)
    cret_return_fee          decimal(7,2)
    cret_return_ship_cost    decimal(7,2)
    cret_refunded_cash       decimal(7,2)
    cret_reversed_charge     decimal(7,2)
    cret_merchant_credit     decimal(7,2)
    cret_reason_id           char(16)
    cret_shipmode_id         char(16)
    cret_catalog_page_id     char(16)
    cret_warehouse_id        char(16)
""",
's_web_returns': """\
    wret_web_page_id         char(16)
    wret_order_id            int32  !
    wret_line_number         int32  !
    wret_item_id             char(16)  !
    wret_return_customer_id  char(16)
    wret_refund_customer_id  char(16)
    wret_return_date         char(10)
    wret_return_time         char(10)
    wret_return_qty          int32
    wret_return_amt          decimal(7,2)
    wret_return_tax          decimal(7,2)
    wret_return_fee          decimal(7,2)
    wret_return_ship_cost    decimal(7,2)
    wret_refunded_cash       decimal(7,2)
    wret_reversed_charge     decimal(7,2)
    wret_account_credit      decimal(7,2)
    wret_reason_id           char(16)
""",
's_inventory': """\
    invn_warehouse_id  char(16)  !
    invn_item_id       char(16)  !
    invn_date          char(10)  !
    invn_qty_on_hand   int32
""",
'delete': """\
    date1  string  !
    date2  string  !
""",
'inventory_delete': """\
    date1  string  !
    date2  string  !
""",
}
