"""Benchmark entry point for the driver: JSON result lines on stdout.

Measurements on the real chip, through the full SQL engine (parse/bind/
execute on device) over generated SF>=1 data:

  1. q3 hot path (scan -> star-join -> group-aggregate -> sort): fact rows
     processed per second per chip, steady-state (post-compile). This is the
     headline metric; vs_baseline compares against the best previously
     recorded round (BENCH_r01.json = 174,607 rows/s), so regressions are
     visible instead of hard-coded away.
  2. Transcode (Load Test) rows/s: SF1 raw CSV -> parquet conversion rate
     (reference metric shape: nds/nds_transcode.py:174-205; BASELINE.md
     milestone #2).
  3. Power-Run geomean: geometric mean of per-query seconds over stream 0 of
     ALL executable templates at this scale, steady-state (reference metric
     shape: nds/nds_power.py:246-281; the TPC-DS north star in BASELINE.md).

Fail-soft contract: a complete JSON result line is (re)printed after the q3
measurement, after the transcode measurement, and after EVERY geomean query —
each line strictly supersedes the previous one, so the driver's `tail -1`
parse always sees the most complete results even if the process is killed
mid-run (the round-3 rc=124 timeout recorded nothing because the single
print sat at the very end).

Every emitted line is COMPACT: headline metrics, geomeans (steady + cold),
stream wall seconds, the engine-vs-sqlite ratio on the shared query subset,
failure counts + failed-query names, and the sf10 block — never the
per-query map (round 5's final line grew to ~1.3 MB of per-query detail and
the driver's tail window truncated its FRONT, losing the headline:
VERDICT item 2). Full per-query times and failure texts are written
atomically to a side file on every update (`detail_file` in the JSON,
default bench_detail.json next to this script, override NDS_BENCH_DETAIL).

After the SF1 stream, a secondary `sf10` block records the same metrics at
NDS scale factor 10 (wall-budgeted, fail-soft), and `sqlite_anchor` embeds
the external sqlite baseline over the identical SF1 stream (computed
offline by tools/sqlite_anchor.py into anchors/sqlite_sf1.json).

Measured SF10 state (2026-07-31, pre-blocked-path): transcode ~222k rows/s
and the first four queries complete (q3 steady 2.6s — 2.4x its SF1 time
for 10x data); query5's three-channel union (64M-row concat capacity x
~10 columns) was the single-chip HBM ceiling — it hard-OOMed the device,
poisoning the backend irrecoverably, so the loop bailed after 3
consecutive OOMs and skipped queries 5-99. The engine now routes
union-feeding-aggregate plans (through projections/filters AND inner
joins — the query5 channel shape) into blocked (morsel-style)
union-aggregation (engine/exec.py:_blocked_union_ctx): each union branch
is evaluated, joined and partially aggregated in bounded row windows
sized from the session HBM budget, so the full concat never materializes
and queries past query5 now record times or per-query errors instead of
an "aborted" marker. The consecutive-OOM bail now only counts OOMs from
queries that did NOT route through the blocked path (those can still
poison the backend).

Env knobs: NDS_BENCH_SCALE (default 1), NDS_BENCH_DATA,
NDS_BENCH_DATA_SF10 (default: NDS_BENCH_DATA + "_sf10.0", else
/tmp/nds_bench_sf10.0), NDS_BENCH_SKIP_GEOMEAN, NDS_BENCH_SKIP_TRANSCODE,
NDS_BENCH_SKIP_SF10, NDS_BENCH_SF10_BUDGET (s), NDS_BENCH_QUERY_TIMEOUT,
NDS_BENCH_QUERY_SUBSET (comma-separated query names, debug aid), and the
engine's NDS_UNION_AGG_WINDOW_ROWS (blocked union-aggregation window size;
default derived from the catalog device budget).
"""

import json
import math
import os
import signal
import statistics
import subprocess
import sys
import time

SCALE = float(os.environ.get("NDS_BENCH_SCALE", "1"))
DATA_DIR = os.environ.get("NDS_BENCH_DATA", f"/tmp/nds_bench_sf{SCALE}")
# best previously recorded single-chip q3 number (BENCH_r01.json)
RECORDED_BASELINE_ROWS_PER_SEC = 174_607
QUERY = """
select d.d_year, i.i_brand_id brand_id, i.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim d, store_sales, item i
where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
  and i.i_manager_id = 10 and d.d_moy = 11
group by d.d_year, i.i_brand, i.i_brand_id
order by d.d_year, sum_agg desc, brand_id
limit 100
"""

# the one result object, mutated in place and re-printed monotonically.
# COMPACT by contract: per-query detail goes to DETAIL (side file), never
# into an emitted line. NDS_BENCH_EMIT_DETAIL=1 (the SF10 isolation child)
# folds the detail into every line so the parent can read it from stdout.
OUT = {
    "metric": "nds_q3_fact_rows_per_sec_per_chip",
    "value": None,
    "unit": "rows/s",
    "vs_baseline": None,
    "scale_factor": SCALE,
}

# full per-query evidence: {"per_query": {...}, "failed": {...},
# "sf10": {"per_query": ..., "failed": ...}} — written to DETAIL_PATH
DETAIL = {}
DETAIL_PATH = os.environ.get(
    "NDS_BENCH_DETAIL",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_detail.json"),
)
SQLITE_PER_QUERY = {}  # loaded by load_sqlite_anchor (shared-subset ratio)


def _current_out():
    """The dict an output line carries right now: OUT, plus the folded-in
    main detail when NDS_BENCH_EMIT_DETAIL is set (the SF10 isolation
    child's stdout protocol). Shared by emit() and the SIGTERM flush so
    the two can never drift."""
    if os.environ.get("NDS_BENCH_EMIT_DETAIL"):
        out = dict(OUT)
        out.update(DETAIL.get("main", {}))
        return out
    return OUT


def emit():
    """Print the current result as one complete JSON line (fail-soft)."""
    print(json.dumps(_current_out()), flush=True)


def write_detail():
    """Atomically persist the per-query detail side file (tmp + rename: a
    SIGKILL mid-write must not leave a torn artifact)."""
    if os.environ.get("NDS_BENCH_SF10_CHILD"):
        # the isolation child reports through stdout (NDS_BENCH_EMIT_DETAIL)
        # and inherits the parent's DETAIL_PATH: writing here would replace
        # the parent's SF1 detail with the child's subset mid-run
        return
    try:
        tmp = DETAIL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(DETAIL, f, indent=1, sort_keys=True)
        os.replace(tmp, DETAIL_PATH)
        OUT["detail_file"] = DETAIL_PATH
    except OSError as exc:
        # detail is evidence, not the contract: never take the run down
        print(f"detail side file failed: {exc}", file=sys.stderr)


def _on_term(signum, frame):
    # the driver's timeout sends SIGTERM before SIGKILL. Every OUT mutation
    # is already followed by emit(), so the last stdout line is current;
    # buffered print/emit here could hit a reentrant-call RuntimeError if
    # the signal lands mid-print (and that error would be swallowed by the
    # geomean loop's except). Raw writes + immediate exit only.
    try:
        # leading newline terminates any half-flushed buffered line so the
        # final line on stdout is always a complete JSON object (the
        # isolation child's detail fold-in rides _current_out, same as
        # every regular emit)
        os.write(1, ("\n" + json.dumps(_current_out()) + "\n").encode())
        os.write(2, b"SIGTERM: flushed partial results\n")
    except OSError:
        pass
    os._exit(0)


def ensure_data(scale=None, data_dir=None, parallel=4):
    scale = SCALE if scale is None else scale
    data_dir = DATA_DIR if data_dir is None else data_dir
    marker = os.path.join(data_dir, ".complete")
    if os.path.exists(marker):
        return
    here = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(
        [
            sys.executable, "-m", "nds_tpu.cli.gen_data",
            "--scale", str(scale), "--parallel", str(parallel),
            "--data_dir", data_dir, "--overwrite_output",
        ],
        check=True,
        cwd=here,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    open(marker, "w").close()


def bench_q3(sess, fact_rows):
    # measured runs execute for real: the session plan-result cache would
    # otherwise turn a re-run into a dict lookup
    sess.conf["engine.plan_cache"] = "off"
    try:
        sess.sql(QUERY).collect()  # warmup: device transfer + compile cache
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            sess.sql(QUERY).collect()
            times.append(time.perf_counter() - t0)
    finally:
        sess.conf["engine.plan_cache"] = "on"
    return fact_rows / statistics.median(times)


def bench_transcode(data_dir=None):
    """CSV -> parquet transcode rate (rows/s) on the flagship fact table,
    hive-partitioned by date (the BASELINE "rows/sec/chip" fact path;
    reference metric shape: nds/nds_transcode.py:174-205)."""
    import shutil
    import tempfile

    from nds_tpu.schema import get_schemas
    from nds_tpu.transcode import transcode_table

    schemas = get_schemas()
    tables = ["store_sales"]
    out = tempfile.mkdtemp(prefix="nds_transcode_bench_")
    rows = 0
    try:
        t0 = time.perf_counter()
        for t in tables:
            rows += transcode_table(
                data_dir or DATA_DIR, out, t, schemas[t],
                output_format="parquet", output_mode="overwrite",
            )
        dt = time.perf_counter() - t0
    finally:
        shutil.rmtree(out, ignore_errors=True)
    return rows / dt


def bench_geomean(sess, block=None, scale=None, wall_budget=None):
    """Steady-state per-query seconds over stream 0 of every template.
    Writes into `block` (default: OUT itself) and re-emits after every
    query (fail-soft). `wall_budget` seconds, if set, stops the loop early
    with a truncation marker (the secondary-scale block must not starve
    the driver's overall budget)."""
    import tempfile

    from nds_tpu.datagen.query_streams import generate_streams
    from nds_tpu.power import gen_sql_from_stream

    block = OUT if block is None else block
    scale = SCALE if scale is None else scale
    wall_start = time.monotonic()
    with tempfile.TemporaryDirectory() as d:
        generate_streams(d, 1, scale, rngseed=19620718)
        queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))
    subset = os.environ.get("NDS_BENCH_QUERY_SUBSET")
    if subset:
        keep = {s.strip() for s in subset.split(",") if s.strip()}
        queries = {n: q for n, q in queries.items() if n in keep}
        if not queries:
            print(f"NDS_BENCH_QUERY_SUBSET={subset!r} matched no queries "
                  f"(names look like 'query3')", file=sys.stderr)
    detail = {}      # name -> {"cold": s, "steady": s}; steady feeds geomean
    failed = {}      # name -> error text (artifact evidence)
    consecutive_oom = 0  # poisoned-backend detector for UNBLOCKED queries

    # daemon-thread timeout: a wedged device runtime blocks inside native
    # code where signals never fire; joining a daemon thread with a timeout
    # still returns control, and daemon threads don't block process exit
    per_query_budget = int(os.environ.get("NDS_BENCH_QUERY_TIMEOUT", "900"))

    def run_with_timeout(q, budget, meta=None):
        import threading

        box = {}

        def work():
            def attempt():
                # error as TEXT, never a live exception: a held traceback
                # would pin the failed attempt's device intermediates
                # through the recovery
                r = None
                try:
                    r = sess.run_script(q)
                    if r is not None:
                        r.collect()
                    err = None
                except Exception as exc:
                    err = str(exc) or type(exc).__name__
                # blocked union-agg marker, read in the query's OWN thread:
                # the Result's executor is per-query (race-free even when a
                # previous query's wedged thread is still running); the
                # session-level marker is the fallback for statements that
                # executed eagerly (CreateTempView) outside this Result.
                # Attribution is script-scoped: a script where one
                # statement routed blocked and a DIFFERENT one OOMed
                # unblocked is still exempted — acceptable slack for a
                # bail heuristic (the abort just needs more evidence)
                ex = getattr(r, "executor", None)
                if getattr(ex, "last_blocked_union", None) is not None or (
                    getattr(sess, "last_blocked_union", None) is not None
                ):
                    box["blocked"] = True
                # out-of-core marker (same contract): a statement that
                # routed through the spill paths gets the same OOM-bail
                # exemption — its OOM is a per-query error, not backend
                # poisoning evidence
                if getattr(ex, "last_spill", None) is not None or (
                    getattr(sess, "last_spill", None) is not None
                ):
                    box["spilled"] = True
                return err

            from nds_tpu import faults

            err = attempt()
            if err is not None and faults.classify(err) == faults.DEVICE_OOM:
                # mid-execution device OOM: drop caches, retry once on a
                # clean device (one OOM must not poison the stream)
                sess.recover_memory("device memory exhausted")
                err = attempt()
                if err is not None and faults.classify(err) == faults.DEVICE_OOM:
                    sess.recover_memory("device memory exhausted")
            if err is None:
                box["ok"] = True
            else:
                box["exc"] = RuntimeError(err)

        th = threading.Thread(target=work, daemon=True)
        th.start()
        th.join(budget)
        finished_late = False
        wedged = False
        if th.is_alive():
            # grace join: distinguish slow-but-progressing from wedged; a
            # still-stuck worker must not race the next query on the shared
            # session, so a true wedge aborts the whole geomean
            th.join(60)
            if th.is_alive():
                wedged = True
            else:
                finished_late = True
        # read the blocked marker AFTER the grace join: a slow blocked query
        # sets box["blocked"] late, and the OOM-bail exemption must still
        # see it when the exception below is raised
        if meta is not None and box.get("blocked"):
            meta["blocked"] = True
        if meta is not None and box.get("spilled"):
            meta["spilled"] = True
        if wedged:
            return "wedged"
        if "exc" in box:  # real failures beat the timeout label
            raise box["exc"]
        if "ok" in box:
            # a query that only finished during the grace join still blew
            # its budget: record it as a timeout, not a success
            return "timeout" if finished_late else "ok"
        return "timeout"

    dbucket = DETAIL.setdefault("main" if block is OUT else "sf10", {})

    def update_out():
        _fill_block(block, detail, failed, wall_start)
        # persistent AOT executable cache evidence (ISSUE 11): hit/miss
        # counts ride every block next to cold_vs_steady, so a round shows
        # whether cold time was compile (misses) or disk (disk_hits) —
        # the isolation children report theirs through the same fold-in
        aot = getattr(sess, "aot_cache", None)
        if aot is not None:
            s = aot.stats
            block["aot_cache"] = {
                "disk_hits": s["disk_hits"],
                "misses": s["misses"],
                "stores": s["stores"],
            }
        dbucket["per_query"] = {
            n: {
                "cold": round(v["cold"], 2),
                "steady": round(v["steady"], 3),
                **({"spill": v["spill"]} if "spill" in v else {}),
                **(
                    {"budget_verdict": v["budget_verdict"]}
                    if "budget_verdict" in v
                    else {}
                ),
            }
            for n, v in detail.items()
        }
        if failed:
            dbucket["failed"] = {n: e[:500] for n, e in failed.items()}
        if block is OUT and SQLITE_PER_QUERY and detail:
            # engine-vs-sqlite on the SHARED subset (queries both engines
            # completed): the anchor's own geomean excludes its timeouts,
            # so the headline ratio must compare like with like
            shared = [n for n in detail if n in SQLITE_PER_QUERY]
            if shared:
                eng = _geomean([detail[n]["steady"] for n in shared])
                sq = _geomean([SQLITE_PER_QUERY[n] for n in shared])
                OUT["sqlite_shared"] = {
                    "queries": len(shared),
                    "engine_geomean_sec": round(eng, 4),
                    "sqlite_geomean_sec": round(sq, 4),
                    "ratio": round(eng / sq, 3),
                }
                # HEADLINE (ROADMAP item 3): the flat ratio rides every
                # OUT line until it crosses 1.0 — `profile --bench` diffs
                # it across rounds
                OUT["sqlite_shared_ratio"] = round(eng / sq, 3)
        write_detail()
        emit()

    for i, (name, q) in enumerate(queries.items()):
        if wall_budget is not None and time.monotonic() - wall_start > wall_budget:
            block["truncated_after"] = i
            emit()
            break
        sess.last_blocked_union = None  # set by blocked union-agg execution
        sess.last_spill = None  # set by out-of-core (spilled) execution
        meta = {}  # run_with_timeout sets meta["blocked"] when it routed
        try:
            t0 = time.perf_counter()
            status = run_with_timeout(q, per_query_budget, meta)
            cold = time.perf_counter() - t0
            if status == "ok":
                # steady-state timing measures true execution: disable the
                # session plan-result cache (the cold pass above keeps it,
                # mirroring a real Power Run sequence where e.g. part2
                # legitimately reuses part1's CTEs)
                sess.conf["engine.plan_cache"] = "off"
                try:
                    t0 = time.perf_counter()
                    status = run_with_timeout(q, per_query_budget, meta)
                    detail[name] = {
                        "cold": cold, "steady": time.perf_counter() - t0,
                    }
                    # per-query out-of-core evidence (ISSUE 9 acceptance):
                    # the spill stats + static budget verdict ride the
                    # bench detail so SF10 isolation output shows WHY a
                    # query completed degraded
                    spill_rec = getattr(sess, "last_spill", None)
                    if spill_rec:
                        detail[name]["spill"] = dict(spill_rec)
                    budget_rec = getattr(sess, "last_plan_budget", None)
                    if isinstance(budget_rec, dict) and budget_rec.get(
                        "verdict"
                    ):
                        detail[name]["budget_verdict"] = budget_rec["verdict"]
                finally:
                    sess.conf["engine.plan_cache"] = "on"
            if status == "ok":
                print(
                    f"[{i + 1}/{len(queries)}] {name}: cold={cold:.1f}s "
                    f"steady={detail[name]['steady']:.2f}s",
                    file=sys.stderr,
                )
                update_out()
                consecutive_oom = 0
                continue
            failed[name] = f"timeout (> {per_query_budget}s, {status})"
            detail.pop(name, None)
            print(f"[{i + 1}/{len(queries)}] {name}: TIMEOUT "
                  f"(> {per_query_budget}s)", file=sys.stderr)
            update_out()
            if status == "wedged":
                print("worker still stuck after grace join - backend "
                      "wedged; aborting geomean", file=sys.stderr)
                break
        except Exception as exc:
            failed[name] = str(exc) or type(exc).__name__
            print(f"[{i + 1}/{len(queries)}] {name}: FAILED {exc}",
                  file=sys.stderr)
            update_out()
            from nds_tpu import faults as _faults

            if _faults.classify(failed[name]) == _faults.DEVICE_OOM:
                # Queries that routed through the blocked union-aggregation
                # path (the SF10 OOM source, query5 and kin) no longer feed
                # the bail: their OOM is a per-query error worth recording,
                # not grounds to skip the stream. But a hard OOM on an
                # UNBLOCKED shape still permanently poisons this backend
                # (the axon terminal stays wedged even after
                # recover_memory), so three of those in a row means every
                # further query would burn the run budget failing the same
                # way.
                if not meta.get("blocked") and not meta.get("spilled"):
                    if os.environ.get("NDS_BENCH_OOM_EXIT"):
                        # SF10 isolation child: a hard OOM on an unblocked
                        # plan permanently poisons this backend, so exit
                        # now (failure already recorded + emitted) and let
                        # the parent restart a fresh process for the
                        # remaining queries
                        block["oom_exit"] = name
                        emit()
                        sys.exit(17)
                    consecutive_oom += 1
                    if consecutive_oom >= 3:
                        block["aborted"] = (
                            "backend poisoned by device OOM on unblocked "
                            "plans; remaining queries skipped"
                        )
                        emit()
                        break
            else:
                consecutive_oom = 0


def _geomean(vals):
    return math.exp(sum(math.log(max(v, 1e-4)) for v in vals) / len(vals))


def _fill_block(block, detail, failed, wall_start):
    """Compact summary fields for an emitted block: steady + cold geomeans,
    cold/steady ratio (VERDICT items 4/5: TPC-DS times actual single
    executions, so cold must be first-class), stream wall clock, failure
    counts + names — never the per-query map (that goes to DETAIL)."""
    if detail:
        block["geomean_query_sec"] = round(
            _geomean([v["steady"] for v in detail.values()]), 4
        )
        block["cold_geomean_query_sec"] = round(
            _geomean([v["cold"] for v in detail.values()]), 4
        )
        block["cold_vs_steady"] = round(
            block["cold_geomean_query_sec"] / block["geomean_query_sec"], 3
        )
        block["slowest5"] = [
            [n, round(v["steady"], 2)]
            for n, v in sorted(
                detail.items(), key=lambda kv: -kv[1]["steady"]
            )[:5]
        ]
    block["geomean_queries"] = len(detail)
    block["stream_wall_sec"] = round(time.monotonic() - wall_start, 1)
    if failed:
        block["failed_queries"] = sorted(failed)
        block["failed_count"] = len(failed)


def load_sqlite_anchor():
    """Embed the offline-computed external sqlite baseline (same data, same
    stream, same host — tools/sqlite_anchor.py) so the engine geomean in
    this artifact always sits next to an independent engine's number."""
    p = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "anchors",
        "sqlite_sf1.json",
    )
    try:
        with open(p) as f:
            a = json.load(f)
    except Exception:
        # the anchor is an optional embellishment: a missing or truncated
        # file must never break the fail-soft artifact contract
        return
    OUT["sqlite_anchor"] = {
        k: a.get(k)
        for k in (
            "engine", "geomean_completed_sec", "completed",
            "timeout_or_failed", "per_query_budget_s",
        )
    }
    SQLITE_PER_QUERY.update(a.get("per_query") or {})


def main():
    if os.environ.get("NDS_BENCH_SF10_CHILD"):
        sf10_child_main()
        return
    signal.signal(signal.SIGTERM, _on_term)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    load_sqlite_anchor()
    ensure_data()

    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    sess = Session()
    schemas = get_schemas()
    for t, schema in schemas.items():
        path = os.path.join(DATA_DIR, t)
        if os.path.isdir(path):
            sess.register_csv_dir(t, path, schema)
    fact_rows = sess.catalog.load("store_sales").nrows

    rows_per_sec = bench_q3(sess, fact_rows)
    OUT["value"] = round(rows_per_sec)
    OUT["vs_baseline"] = round(
        rows_per_sec / RECORDED_BASELINE_ROWS_PER_SEC, 3
    )
    emit()  # q3 headline lands no matter what happens later

    if not os.environ.get("NDS_BENCH_SKIP_TRANSCODE"):
        try:
            OUT["transcode_rows_per_sec"] = round(bench_transcode())
        except Exception as exc:
            print(f"transcode bench failed: {exc}", file=sys.stderr)
        emit()

    if not os.environ.get("NDS_BENCH_SKIP_GEOMEAN"):
        bench_geomean(sess)
    emit()

    if not os.environ.get("NDS_BENCH_SKIP_SF10") and SCALE == 1.0:
        try:
            bench_sf10(sess)
        except Exception as exc:
            OUT.setdefault("sf10", {})["error"] = str(exc)[:500]
        emit()

    if os.environ.get("NDS_BENCH_MAINT_UNDER_LOAD"):
        # opt-in robustness block: DM_* commits + a lease-safe vacuum
        # racing a query stream over a tiny lakehouse warehouse, reported
        # as maintenance throughput x query p99 degradation (the
        # full_bench maintenance_under_load phase's metric, embedded in
        # the bench artifact so rounds can track it). Fail-soft.
        try:
            OUT["maintenance_under_load"] = bench_maintenance_under_load()
        except Exception as exc:
            OUT["maintenance_under_load"] = {"error": str(exc)[:500]}
        emit()

    if os.environ.get("NDS_BENCH_SERVE"):
        # opt-in serve block (NDS_BENCH_SERVE=1): the closed-loop
        # multi-client QPS x p99 scenario (tools/serve_bench.py) beside
        # the TPC-DS composite — point lookups + heavy aggregates + DM
        # writes against the serve endpoint, snapshot-consistency
        # asserted per response. Fail-soft like the block above.
        try:
            OUT["serve"] = bench_serve()
        except Exception as exc:
            OUT["serve"] = {"error": str(exc)[:500]}
        emit()

    # carry-forward hygiene (ROADMAP): every round auto-compares its
    # sqlite_shared headline against the newest stored BENCH_r*.json via
    # the profiler's --bench comparison, instead of relying on someone
    # remembering the manual `profile --bench OLD NEW` invocation
    compare_against_baseline()
    emit()


def bench_serve():
    """Run tools/serve_bench.run_bench (in-process, ephemeral port) over
    the marker-cached SF0.01 lakehouse and return the compact headline
    fields. Knobs: NDS_BENCH_SERVE_CLIENTS (4), NDS_BENCH_SERVE_DURATION
    seconds (30)."""
    import importlib.util

    here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(here, "tools", "serve_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    r = mod.run_bench(
        clients=int(os.environ.get("NDS_BENCH_SERVE_CLIENTS", "4")),
        duration_s=float(os.environ.get("NDS_BENCH_SERVE_DURATION", "30")),
    )
    DETAIL["serve"] = r
    return {
        k: r.get(k)
        for k in (
            "qps", "p50_ms", "p99_ms", "scraped_p99_ms", "requests",
            "completed", "http_5xx", "rejected_429", "snapshot_violations",
            "dm_commits", "wall_s", "clients", "workers",
        )
    }


def compare_against_baseline():
    """Auto round comparison: diff this run's sqlite_shared headline
    against the stored baseline round (NDS_BENCH_BASELINE, else the
    newest BENCH_r*.json next to this script) through the same
    `profile --bench` comparison the manual invocation uses. Fail-soft:
    a malformed baseline must never cost the round its metrics."""
    try:
        import glob
        import tempfile

        here = os.path.dirname(os.path.abspath(__file__))
        base = os.environ.get("NDS_BENCH_BASELINE")
        if not base:
            rounds = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
            base = rounds[-1] if rounds else None
        if not base or not OUT.get("sqlite_shared"):
            return
        from nds_tpu.cli.profile import _compare_sqlite_shared

        fd, tmp = tempfile.mkstemp(suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(OUT, f)
            recs = _compare_sqlite_shared(base, tmp)
        finally:
            os.unlink(tmp)
        rec = next(
            (r for r in recs if r.get("change") in ("headline", "regression")),
            None,
        )
        if rec is not None:
            OUT["baseline_compare"] = {
                "baseline": os.path.basename(base),
                "old_ratio": rec.get("old_ratio"),
                "new_ratio": rec.get("new_ratio"),
                "regressed": rec.get("change") == "regression",
            }
    except Exception as exc:
        OUT["baseline_compare"] = {"error": str(exc)[:200]}


def bench_maintenance_under_load():
    """Maintenance-under-load at SF0.01 (NDS_BENCH_MAINT_UNDER_LOAD=1):
    build (once, marker-cached) a tiny raw set + refresh set + lakehouse
    warehouse + query stream under NDS_BENCH_MUL_DIR (default
    /tmp/nds_bench_mul), then run nds_tpu.maintenance.
    run_maintenance_under_load over a small query subset. Returns the
    compact report dict (p99 degradation + dm throughput)."""
    base = os.environ.get("NDS_BENCH_MUL_DIR", "/tmp/nds_bench_mul")
    raw = os.path.join(base, "raw")
    refresh = os.path.join(base, "refresh")
    wh = os.path.join(base, "warehouse")
    streams = os.path.join(base, "streams")
    here = os.path.dirname(os.path.abspath(__file__))
    ensure_data(scale=0.01, data_dir=raw, parallel=2)
    if not os.path.exists(os.path.join(refresh, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.gen_data", "--scale",
             "0.01", "--parallel", "2", "--data_dir", refresh,
             "--update", "1", "--overwrite_output"],
            check=True, cwd=here, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        open(os.path.join(refresh, ".complete"), "w").close()
    if not os.path.exists(os.path.join(wh, ".complete")):
        subprocess.run(
            [sys.executable, "-m", "nds_tpu.cli.transcode", raw, wh,
             os.path.join(wh, "load.report"), "--output_format",
             "lakehouse", "--output_mode", "overwrite"],
            check=True, cwd=here, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        open(os.path.join(wh, ".complete"), "w").close()
    stream_file = os.path.join(streams, "query_1.sql")
    if not os.path.exists(stream_file):
        from nds_tpu.datagen.query_streams import generate_streams

        generate_streams(streams, 2, 0.01, rngseed=19620718)

    from nds_tpu.maintenance import run_maintenance_under_load

    report = run_maintenance_under_load(
        warehouse_path=wh,
        refresh_data_path=refresh,
        stream_file=stream_file,
        time_log_output_path=os.path.join(base, "mul_time.csv"),
        report_path=os.path.join(base, "mul_report.json"),
        spec_queries=os.environ.get(
            "NDS_BENCH_MUL_FUNCS", "LF_SS,DF_SS"
        ).split(","),
        sub_queries=os.environ.get(
            "NDS_BENCH_MUL_QUERIES", "query3,query7,query52"
        ).split(","),
    )
    # compact: the artifact line carries the headline fields only
    return {
        k: report.get(k)
        for k in (
            "queries", "query_p99_ms_solo", "query_p99_ms_under_load",
            "query_p99_degradation", "dm_functions", "dm_failed",
            "dm_functions_per_s", "vacuums", "vacuum_files_removed",
            "under_load_failed",
        )
    }


def _sf10_data_dir() -> str:
    """SF10 data dir: NDS_BENCH_DATA_SF10 wins outright; else a
    "_sf10.0"-suffixed sibling of NDS_BENCH_DATA (an operator redirecting
    SF1 data to a larger volume gets SF10 on the same volume, not ~10 GB
    silently dumped under /tmp); /tmp only as the last-resort default."""
    explicit = os.environ.get("NDS_BENCH_DATA_SF10")
    if explicit:
        return explicit
    base = os.environ.get("NDS_BENCH_DATA")
    if base:
        return base.rstrip("/") + "_sf10.0"
    return "/tmp/nds_bench_sf10.0"


def _sf10_session(data_dir):
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    sess = Session()
    # SF10 fact caps are 32M rows: a single multi-column pair table is
    # GB-scale, and one hard OOM poisons the backend for the whole rest of
    # the stream (axon terminal). Trade table-reload time for headroom.
    sess.catalog.DEVICE_BUDGET_BYTES = 3 << 30
    for t, schema in get_schemas().items():
        path = os.path.join(data_dir, t)
        if os.path.isdir(path):
            sess.register_csv_dir(t, path, schema)
    return sess


def _stream_query_names(scale):
    """Query names of stream 0 at `scale`, in stream order (the parent
    needs them to assign work to isolation children and to identify the
    query a dead child was running)."""
    import tempfile

    from nds_tpu.datagen.query_streams import generate_streams
    from nds_tpu.power import gen_sql_from_stream

    with tempfile.TemporaryDirectory() as d:
        generate_streams(d, 1, scale, rngseed=19620718)
        return list(gen_sql_from_stream(os.path.join(d, "query_0.sql")))


def _last_json_line(text):
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


_OOM_EXIT_RC = 17  # child recorded the OOM itself before exiting


def sf10_child_main():
    """Isolation child (NDS_BENCH_SF10_CHILD=1): run the assigned SF10
    query subset (NDS_BENCH_QUERY_SUBSET) on a fresh backend, emitting
    fail-soft JSON lines WITH per-query detail (the parent reads them from
    stdout). Exits 17 after recording an unblocked device OOM so the
    parent restarts a clean process for the remaining queries."""
    signal.signal(signal.SIGTERM, _on_term)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    os.environ["NDS_BENCH_EMIT_DETAIL"] = "1"
    os.environ["NDS_BENCH_OOM_EXIT"] = "1"
    sess = _sf10_session(_sf10_data_dir())
    budget = int(os.environ.get("NDS_BENCH_SF10_WALL_BUDGET", "2700"))
    bench_geomean(sess, block=OUT, scale=10, wall_budget=budget)
    emit()


def bench_sf10(sess_sf1):
    """Secondary block at SF10 (BASELINE ladder: the next rung after SF1;
    store_sales = 28.8M rows — fits HBM, stresses every capacity
    heuristic). Fail-soft into OUT['sf10'].

    Per-query-failure SUBPROCESS ISOLATION (VERDICT item 8): queries run
    in a child process; when one dies on a device OOM (or crashes/wedges),
    only THAT query is recorded as failed and a fresh child continues with
    the remaining ones — one OOM no longer poisons/aborts the rest of the
    block. NDS_BENCH_SF10_ISOLATION=inproc restores the single-process
    path (debug aid). The loop is wall-budgeted; a SIGTERM at any point
    still flushes whatever the block has recorded so far."""
    block = OUT.setdefault("sf10", {})
    data_dir = _sf10_data_dir()
    ensure_data(scale=10, data_dir=data_dir, parallel=8)
    block["transcode_rows_per_sec"] = round(bench_transcode(data_dir))
    emit()
    # free the SF1 session's device residency before SF10 work starts
    sess_sf1.recover_memory("switching to SF10 data")
    budget = int(os.environ.get("NDS_BENCH_SF10_BUDGET", "2700"))
    if os.environ.get("NDS_BENCH_SF10_ISOLATION", "process") == "inproc":
        bench_geomean(
            _sf10_session(data_dir), block=block, scale=10,
            wall_budget=budget,
        )
        return

    here = os.path.dirname(os.path.abspath(__file__))
    names = _stream_query_names(scale=10)
    subset = os.environ.get("NDS_BENCH_QUERY_SUBSET")
    if subset:
        keep = {s.strip() for s in subset.split(",") if s.strip()}
        names = [n for n in names if n in keep]
    # shared AOT executable cache for the isolation children (ISSUE 11):
    # every fresh child process warms its fused-pipeline executables from
    # disk instead of re-paying the whole compile footprint — the explicit
    # env pin means restarted children (and a restarted parent) agree on
    # ONE directory even if the ambient default ever changes mid-round
    from nds_tpu.engine.aotcache import resolve_aot_cache_dir

    aot_dir = resolve_aot_cache_dir()
    t_start = time.monotonic()
    detail = {}  # name -> {"cold", "steady"} (floats, parent-side)
    failed = {}
    dbucket = DETAIL.setdefault("sf10", {})

    def update_block():
        _fill_block(block, detail, failed, t_start)
        dbucket["per_query"] = dict(detail)
        if failed:
            dbucket["failed"] = {n: e[:500] for n, e in failed.items()}
        write_detail()
        emit()

    # one round-level trace context: every isolation child parents to it
    from nds_tpu.obs.trace import resolve_trace_context

    round_ctx = resolve_trace_context("sf10-round")
    remaining = list(names)
    while remaining:
        left = budget - (time.monotonic() - t_start)
        if left <= 60:
            block["truncated_after"] = len(names) - len(remaining)
            update_block()
            break
        env = dict(os.environ)
        env["NDS_BENCH_SF10_CHILD"] = "1"
        env["NDS_BENCH_QUERY_SUBSET"] = ",".join(remaining)
        env["NDS_BENCH_SF10_WALL_BUDGET"] = str(int(left))
        if aot_dir:
            env["NDS_AOT_CACHE_DIR"] = aot_dir
        # per-child trace context: the isolation child's event files (and
        # any failure bundle it flushes before dying) carry a trace_id the
        # parent minted — attribution survives pid recycling across the
        # many children a long SF10 round respawns
        round_ctx.child(f"sf10-{len(remaining)}left").export(env)
        stderr_tail = ""
        budget_kill = False
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, cwd=here, capture_output=True, text=True,
                timeout=left + 120,
            )
            rc, out_text = p.returncode, p.stdout
            stderr_tail = (p.stderr or "")[-300:]
        except subprocess.TimeoutExpired as te:
            # the parent's own wall budget (plus grace) expired: this is
            # TRUNCATION, not a query failure — the query the child was on
            # must not enter `failed` as if it broke
            rc = -9
            budget_kill = True
            out_text = te.stdout or ""
            if isinstance(out_text, bytes):
                out_text = out_text.decode("utf-8", "replace")
            err_text = te.stderr or ""
            if isinstance(err_text, bytes):
                err_text = err_text.decode("utf-8", "replace")
            stderr_tail = err_text[-300:]
        child = _last_json_line(out_text) or {}
        cpq = child.get("per_query") or {}
        cfail = child.get("failed") or {}
        caot = child.get("aot_cache")
        if isinstance(caot, dict):
            # accumulate children's cache traffic: across a whole round
            # disk_hits should dominate misses once the first child warmed
            # each shape (the "recompile the world per child" fix, visible
            # in the artifact)
            agg = block.setdefault(
                "aot_cache", {"disk_hits": 0, "misses": 0, "stores": 0}
            )
            for k in ("disk_hits", "misses", "stores"):
                agg[k] += int(caot.get(k) or 0)
        detail.update(
            {n: v for n, v in cpq.items() if isinstance(v, dict)}
        )
        failed.update(cfail)
        covered = set(cpq) | set(cfail)
        new_remaining = [n for n in remaining if n not in covered]
        progressed = bool(covered & set(remaining))
        if budget_kill:
            remaining = new_remaining
            block["truncated_after"] = len(names) - len(remaining)
            update_block()
            break
        if new_remaining and (
            not progressed or rc not in (0, _OOM_EXIT_RC)
        ):
            # the child died mid-query (or produced nothing): blame the
            # first query it had not covered, then move past it — without
            # this the loop could respawn children forever on a
            # reproducible early crash
            victim = new_remaining.pop(0)
            failed[victim] = (
                f"subprocess died (rc={rc}): {stderr_tail}"
                if rc != 0
                else "subprocess made no progress"
            )
        remaining = new_remaining
        update_block()
        # anything left (child OOM-exit, crash, wedge-abort, or its own
        # wall-budget stop) loops back: the budget check at the top
        # decides whether a fresh child continues


if __name__ == "__main__":
    main()
