"""Benchmark entry point for the driver: ONE JSON line on stdout.

Measures the NDS Power-Run hot path on the real chip: a q3-shaped
scan -> star-join -> filter -> group-aggregate -> sort over generated
store_sales data, through the full SQL engine (parse/bind/execute on device).
Metric: fact rows processed per second per chip, steady-state (post-compile).

The reference publishes no numbers (BASELINE.md); vs_baseline is reported
against the configured target in BASELINE.json terms as 1.0 until a recorded
baseline exists.
"""

import json
import os
import statistics
import subprocess
import sys
import time

SCALE = float(os.environ.get("NDS_BENCH_SCALE", "0.1"))
DATA_DIR = os.environ.get("NDS_BENCH_DATA", f"/tmp/nds_bench_sf{SCALE}")
QUERY = """
select d.d_year, i.i_brand_id brand_id, i.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim d, store_sales, item i
where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
  and i.i_manager_id = 10 and d.d_moy = 11
group by d.d_year, i.i_brand, i.i_brand_id
order by d.d_year, sum_agg desc, brand_id
limit 100
"""


def ensure_data():
    marker = os.path.join(DATA_DIR, ".complete")
    if os.path.exists(marker):
        return
    here = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(
        [
            sys.executable, "-m", "nds_tpu.cli.gen_data",
            "--scale", str(SCALE), "--parallel", "2",
            "--data_dir", DATA_DIR, "--overwrite_output",
        ],
        check=True,
        cwd=here,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    open(marker, "w").close()


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    ensure_data()

    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    sess = Session()
    schemas = get_schemas()
    for t in ("store_sales", "item", "date_dim"):
        sess.register_csv_dir(t, os.path.join(DATA_DIR, t), schemas[t])
    fact_rows = sess.catalog.load("store_sales").nrows

    # warmup: trigger device transfer + compile cache
    sess.sql(QUERY).collect()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        sess.sql(QUERY).collect()
        times.append(time.perf_counter() - t0)
    t = statistics.median(times)
    rows_per_sec = fact_rows / t
    print(
        json.dumps(
            {
                "metric": "nds_q3_fact_rows_per_sec_per_chip",
                "value": round(rows_per_sec),
                "unit": "rows/s",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
