"""Benchmark entry point for the driver: ONE JSON line on stdout.

Two measurements on the real chip, through the full SQL engine
(parse/bind/execute on device) over generated SF>=1 data:

  1. q3 hot path (scan -> star-join -> group-aggregate -> sort): fact rows
     processed per second per chip, steady-state (post-compile). This is the
     headline metric; vs_baseline compares against the best previously
     recorded round (BENCH_r01.json = 174,607 rows/s), so regressions are
     visible instead of hard-coded away.
  2. Power-Run geomean: geometric mean of per-query seconds over stream 0 of
     ALL executable templates at this scale, steady-state (reference metric
     shape: nds/nds_power.py:246-281; the TPC-DS north star in BASELINE.md).

Env knobs: NDS_BENCH_SCALE (default 1), NDS_BENCH_DATA, NDS_BENCH_SKIP_GEOMEAN.
"""

import json
import math
import os
import statistics
import subprocess
import sys
import time

SCALE = float(os.environ.get("NDS_BENCH_SCALE", "1"))
DATA_DIR = os.environ.get("NDS_BENCH_DATA", f"/tmp/nds_bench_sf{SCALE}")
# best previously recorded single-chip q3 number (BENCH_r01.json)
RECORDED_BASELINE_ROWS_PER_SEC = 174_607
QUERY = """
select d.d_year, i.i_brand_id brand_id, i.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim d, store_sales, item i
where d.d_date_sk = ss_sold_date_sk and ss_item_sk = i.i_item_sk
  and i.i_manager_id = 10 and d.d_moy = 11
group by d.d_year, i.i_brand, i.i_brand_id
order by d.d_year, sum_agg desc, brand_id
limit 100
"""


def ensure_data():
    marker = os.path.join(DATA_DIR, ".complete")
    if os.path.exists(marker):
        return
    here = os.path.dirname(os.path.abspath(__file__))
    subprocess.run(
        [
            sys.executable, "-m", "nds_tpu.cli.gen_data",
            "--scale", str(SCALE), "--parallel", "4",
            "--data_dir", DATA_DIR, "--overwrite_output",
        ],
        check=True,
        cwd=here,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    open(marker, "w").close()


def bench_q3(sess, fact_rows):
    sess.sql(QUERY).collect()  # warmup: device transfer + compile cache
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        sess.sql(QUERY).collect()
        times.append(time.perf_counter() - t0)
    return fact_rows / statistics.median(times)


def bench_geomean(sess):
    """Steady-state per-query seconds over stream 0 of every template."""
    import tempfile

    from nds_tpu.datagen.query_streams import generate_streams
    from nds_tpu.power import gen_sql_from_stream

    with tempfile.TemporaryDirectory() as d:
        generate_streams(d, 1, SCALE, rngseed=19620718)
        queries = gen_sql_from_stream(os.path.join(d, "query_0.sql"))
    per_query = {}
    failed = []

    # daemon-thread timeout: a wedged device runtime blocks inside native
    # code where signals never fire; joining a daemon thread with a timeout
    # still returns control, and daemon threads don't block process exit
    per_query_budget = int(os.environ.get("NDS_BENCH_QUERY_TIMEOUT", "900"))
    consecutive_timeouts = 0

    def run_with_timeout(q, budget):
        import threading

        box = {}

        def work():
            try:
                r = sess.run_script(q)
                if r is not None:
                    r.collect()
                box["ok"] = True
            except Exception as exc:  # surfaced to the caller
                box["exc"] = exc

        th = threading.Thread(target=work, daemon=True)
        th.start()
        th.join(budget)
        if th.is_alive():
            # grace join: distinguish slow-but-progressing from wedged; a
            # still-stuck worker must not race the next query on the shared
            # session, so a true wedge aborts the whole geomean
            th.join(60)
            if th.is_alive():
                return "wedged"
        if "exc" in box:  # real failures beat the timeout label
            raise box["exc"]
        return "ok" if "ok" in box else "timeout"

    for i, (name, q) in enumerate(queries.items()):
        try:
            t0 = time.perf_counter()
            status = run_with_timeout(q, per_query_budget)
            cold = time.perf_counter() - t0
            if status == "ok":
                t0 = time.perf_counter()
                status = run_with_timeout(q, per_query_budget)
                per_query[name] = time.perf_counter() - t0
            if status == "ok":
                consecutive_timeouts = 0
                print(
                    f"[{i + 1}/{len(queries)}] {name}: cold={cold:.1f}s "
                    f"steady={per_query[name]:.2f}s",
                    file=sys.stderr,
                )
                continue
            failed.append(name)
            per_query.pop(name, None)
            consecutive_timeouts += 1
            print(f"[{i + 1}/{len(queries)}] {name}: TIMEOUT "
                  f"(> {per_query_budget}s)", file=sys.stderr)
            if status == "wedged":
                print("worker still stuck after grace join - backend "
                      "wedged; aborting geomean", file=sys.stderr)
                break
            if consecutive_timeouts >= 3:
                # uniformly slow backend: don't burn ~99 x budget seconds
                print("3 consecutive timeouts - aborting geomean",
                      file=sys.stderr)
                break
        except Exception as exc:
            failed.append(name)
            print(f"[{i + 1}/{len(queries)}] {name}: FAILED {exc}",
                  file=sys.stderr)
    if not per_query:
        return None, 0, failed
    geo = math.exp(sum(math.log(max(t, 1e-4)) for t in per_query.values())
                   / len(per_query))
    return geo, len(per_query), failed


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    ensure_data()

    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    sess = Session()
    schemas = get_schemas()
    for t, schema in schemas.items():
        path = os.path.join(DATA_DIR, t)
        if os.path.isdir(path):
            sess.register_csv_dir(t, path, schema)
    fact_rows = sess.catalog.load("store_sales").nrows

    rows_per_sec = bench_q3(sess, fact_rows)
    out = {
        "metric": "nds_q3_fact_rows_per_sec_per_chip",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / RECORDED_BASELINE_ROWS_PER_SEC, 3),
        "scale_factor": SCALE,
    }
    if not os.environ.get("NDS_BENCH_SKIP_GEOMEAN"):
        geo, nq, failed = bench_geomean(sess)
        out["geomean_query_sec"] = None if geo is None else round(geo, 4)
        out["geomean_queries"] = nq
        if failed:
            out["failed_queries"] = failed
    print(json.dumps(out))


if __name__ == "__main__":
    main()
